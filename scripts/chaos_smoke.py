#!/usr/bin/env python
"""Chaos smoke — the resilience-layer CI gate.

Fires every :data:`deeplearning4j_tpu.resilience.FAULT_KINDS` injector
kind against a real (tiny, CPU-sized) training run and a real
``GenerationServer``, then asserts:

* training still completes with the uninterrupted run's EXACT final
  loss and parameters (kill-and-resume is bit-identical; NaN steps are
  skipped; a failed checkpoint write degrades, not kills);
* a PIPELINE trainer preempted under ``fleet_resume_fit`` rendezvouses,
  agrees a resume step, restacks the restored tree into the
  pipe-sharded params and finishes (coordinated-restart + pipeline
  resume, in the single-process degenerate);
* decode-server recovery is ZERO-DOWNTIME: a scheduler crash salvages
  every in-flight slot's KV (all callers complete byte-identically,
  nothing resubmitted), and a stuck tick with a poisoned slot drops
  ONLY that slot — the two unaffected callers finish offline-identical
  and the implicated one rides a submit retry through;
* a SAMPLED SPECULATIVE slot (ISSUE 20) survives the same tick crash:
  the watchdog salvages its draft table and held residual/PRNG state
  — the same-seed sampled stream completes byte-identical to the
  uncrashed run, its greedy pool neighbour offline-identical;
* a MESH-SHARDED tp=2 replica (ISSUE 17) survives the same tick crash
  — the unchanged watchdog salvages every slot into the rebuilt
  sharded pool (byte-identical, ``tp_device_loss`` flight event on
  the wire) — and a mixed fleet whose tp=2 replica is killed
  mid-decode migrates every request byte-identical onto the
  single-chip survivor (``outcome="migrated"`` on the scrape);
* a DISAGGREGATED fleet (prefill + decode roles, ISSUE 14) survives a
  SIGKILL of its prefill replica mid-handoff: the staged requests
  re-place through the existing migration machinery onto the decode
  survivor and complete byte-identical to offline ``generate()``;
* an induced OVERLOAD STORM (ISSUE 18) walks the production front
  door end to end: the admission projection sheds the batch tenant
  with a server-advised retry-after, the degradation ladder climbs to
  the shed rung and walks back down once the burn clears, interactive
  traffic rides through with zero deadline misses (degraded outputs
  byte-identical to the capped offline prefix), a near-deadline
  request races a hedge whose loser is cancelled, and the whole
  ladder walk is replayed from the recorded TSDB history over
  ``/query``;
* every recovery event landed in the telemetry registry
  (``faults_injected_total{kind=...}`` for each kind, resume/preempt/
  bad-step/watchdog counters, ``fleet_*`` + ``kv_slots_*`` counters,
  submit retry histograms) — checked over a real HTTP scrape via the
  helpers in ``check_telemetry.py``.

Runs on CPU inside the tier-1 budget — wired into
``tests/test_resilience.py::test_chaos_smoke`` un-marked, and runnable
standalone:

    JAX_PLATFORMS=cpu python scripts/chaos_smoke.py
"""
import importlib.util
import json
import os
import shutil
import sys
import tempfile
import threading
import time
import urllib.request

# the pipeline chaos run needs >= 2 devices; force a virtual CPU pair
# BEFORE jax initializes (no-op in-process under tests/conftest.py,
# which already forces 8)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

# each training-side kind once, at deterministic iterations of a
# 3-epoch x 6-batch run (18 iterations; checkpoints every 2)
TRAIN_PLAN = ["data_stall@1:0.05", "nan_loss@3", "checkpoint_fail@4",
              "step_exception@7", "preempt@12"]
# serving scenario 1 — scheduler crash mid-service: enqueue window,
# 4 throttled passes (every slot fills and decodes a few ticks), then
# pass 5 kills the scheduler thread.  Scenario 2 — stuck tick with a
# poisoned slot: 15 throttled passes (budgets stay un-drained while
# the main thread NaN-poisons the victim's KV row), then pass 16
# hangs past the 0.8s deadline -> watchdog salvage recovery.
from deeplearning4j_tpu.resilience.faults import (poison_slot_kv,
                                                  throttled_stall_plan)

SERVE_CRASH_PLAN = throttled_stall_plan(4, "serve_tick_fail@5")
SERVE_STALL_PLAN = throttled_stall_plan(15, "serve_tick_stall@16:2.2")
# serving scenario 3 (ISSUE 17) — the SAME crash shape against a tp=2
# MESH-SHARDED server: from the host a failed dispatch on a multi-chip
# replica is indistinguishable from losing one chip of the tp group
# mid-tick, so the unchanged watchdog must salvage the sharded pool
# and the tp_device_loss flight event must land with the slice
SERVE_TP_CRASH_PLAN = throttled_stall_plan(4, "serve_tick_fail@5")
# serving scenario (ISSUE 20) — the crash shape against a SAMPLED
# speculative server (fixed K: byte pins need replayable depth)
SERVE_SPEC_CRASH_PLAN = throttled_stall_plan(4, "serve_tick_fail@5")


def _load_check_telemetry():
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "check_telemetry.py")
    spec = importlib.util.spec_from_file_location("check_telemetry", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main(min_history_s: float = 60.0) -> int:
    from deeplearning4j_tpu import (MultiLayerNetwork,
                                    NeuralNetConfiguration, resilience,
                                    telemetry)
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterator import ListDataSetIterator
    from deeplearning4j_tpu.models.generation import TransformerGenerator
    from deeplearning4j_tpu.nn.conf.layers_core import (DenseLayer,
                                                        OutputLayer)
    from deeplearning4j_tpu.optimize.updaters import Adam
    from deeplearning4j_tpu.parallel import (CheckpointListener,
                                             GenerationServer)
    from deeplearning4j_tpu.resilience import (BadStepPolicy,
                                               FaultInjector,
                                               InjectedFault,
                                               auto_resume_fit)
    from deeplearning4j_tpu.zoo.gpt import Gpt

    ct = _load_check_telemetry()
    registry = telemetry.get_registry()
    problems = []
    # ISSUE 16: record the whole run into the embedded time-series
    # store at beacon cadence — the SLO kill at the end must find
    # >= min_history_s of pre-crash history in its bundle, and the
    # live /query read must reproduce the burn window
    tsdb = telemetry.get_tsdb()
    tsdb.start_recorder(registry, interval_s=1.0)

    def counter(name):
        return registry.counter(name)

    fault_counter = registry.counter("faults_injected_total",
                                     labelnames=("kind",))

    def model():
        conf = (NeuralNetConfiguration.builder().seed(7)
                .updater(Adam(learning_rate=1e-2)).list()
                .layer(DenseLayer(n_in=8, n_out=16, activation="relu"))
                .layer(OutputLayer(n_out=4, activation="softmax",
                                   loss="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(0)
    x = rng.normal(size=(96, 8)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 96)]

    def data():
        return ListDataSetIterator(DataSet(x, y).batch_by(16))

    # -- uninterrupted reference ---------------------------------------
    ref = model()
    ref_loss = ref.fit(data(), n_epochs=3, async_prefetch=False)

    # -- training fault matrix -----------------------------------------
    faults_before = {k: fault_counter.labels(kind=k).value
                     for k in resilience.FAULT_KINDS}
    resumes0 = counter("train_resumes_total").value
    preempts0 = counter("train_preemptions_total").value
    skipped0 = counter("bad_steps_skipped_total").value
    ckfail0 = counter("checkpoint_failures_total").value

    m = model()
    with tempfile.TemporaryDirectory() as d:
        ck = CheckpointListener(os.path.join(d, "ck"),
                                save_every_n_iterations=2)
        m.set_listeners(ck, BadStepPolicy(max_consecutive=3,
                                          checkpoint=ck))
        with FaultInjector(TRAIN_PLAN):
            loss = auto_resume_fit(
                lambda: m.fit(data(), n_epochs=3, async_prefetch=False,
                              resume=True),
                max_restarts=4, retry_on=(InjectedFault,))
        ck.ckpt.close()
    if m.epoch_count != 3:
        problems.append(f"training finished {m.epoch_count}/3 epochs")
    if loss is None or not np.isfinite(loss):
        problems.append(f"post-chaos final loss {loss}")
    if counter("train_resumes_total").value - resumes0 < 2:
        problems.append("expected >= 2 checkpoint resumes "
                        "(step_exception + preempt restarts)")
    if counter("train_preemptions_total").value - preempts0 != 1:
        problems.append("train_preemptions_total did not grow by 1")
    if counter("bad_steps_skipped_total").value - skipped0 != 1:
        problems.append("bad_steps_skipped_total did not grow by 1")
    if counter("checkpoint_failures_total").value - ckfail0 != 1:
        problems.append("checkpoint_failures_total did not grow by 1")

    # -- preempt-only: kill-and-resume must be BIT-IDENTICAL -----------
    # (the combined matrix above legitimately diverges from the
    # reference: its NaN-poisoned update is skipped where the
    # uninterrupted run applied the clean one)
    m2 = model()
    with tempfile.TemporaryDirectory() as d:
        ck2 = CheckpointListener(os.path.join(d, "ck"),
                                 save_every_n_iterations=5)
        m2.set_listeners(ck2)
        with FaultInjector(["preempt@8"]):
            loss2 = auto_resume_fit(
                lambda: m2.fit(data(), n_epochs=3, async_prefetch=False,
                               resume=True), max_restarts=2)
        ck2.ckpt.close()
    if loss2 is None or float(loss2) != float(ref_loss):
        problems.append(
            f"preempt+resume final loss {loss2} != uninterrupted "
            f"{ref_loss} (kill-and-resume not bit-identical)")

    # -- preempt-in-pipeline: coordinated fleet restart + pipeline
    # resume (single-process degenerate of the multiproc chaos test) --
    import jax
    fleet_resumed = registry.counter(
        "fleet_resumes_total",
        labelnames=("outcome",)).labels(outcome="resumed")
    fleet_shrink = registry.counter(
        "fleet_elastic_resumes_total",
        labelnames=("direction",)).labels(direction="shrink")
    fleet_b0 = counter("fleet_preempt_broadcasts_total").value
    fleet_r0 = fleet_resumed.value
    if jax.device_count() < 2:
        problems.append(f"pipeline chaos run needs >= 2 devices, have "
                        f"{jax.device_count()}")
    else:
        from deeplearning4j_tpu.parallel.mesh import MeshConfig
        from deeplearning4j_tpu.parallel.trainer import ShardedTrainer
        from deeplearning4j_tpu.resilience import fleet_resume_fit
        rng_p = np.random.default_rng(4)
        px = rng_p.integers(0, 32, (16, 8)).astype(np.int32)
        py = np.roll(px, -1, axis=1)
        gpt_p = Gpt(vocab_size=32, max_len=8, d_model=16, n_layers=2,
                    n_heads=2, d_ff=32, seq_len=8, compute_dtype=None,
                    use_flash=False, seed=9).init_graph()
        tr_p = ShardedTrainer(gpt_p, MeshConfig(pipeline=2), n_micro=2)

        def data_p():
            return ListDataSetIterator(DataSet(px, py).batch_by(8))

        with tempfile.TemporaryDirectory() as d:
            # world=2 recorded beside every save: the shrink scenario
            # below resumes the SAME checkpoints on a 1-way world
            ck_p = CheckpointListener(os.path.join(d, "ck"),
                                      save_every_n_iterations=2,
                                      world=2)
            gpt_p.set_listeners(ck_p)
            with FaultInjector(["preempt@2"]):
                loss_p = fleet_resume_fit(
                    lambda: tr_p.fit(data_p(), n_epochs=2, resume=True),
                    mesh=tr_p.mesh, checkpoint=ck_p, max_restarts=2,
                    world=2)
            ck_p.ckpt.wait()
            if gpt_p.epoch_count != 2:
                problems.append(f"pipeline chaos run finished "
                                f"{gpt_p.epoch_count}/2 epochs")
            if loss_p is None or not np.isfinite(loss_p):
                problems.append(f"pipeline post-preempt loss {loss_p}")

            # -- ELASTIC SHRINK (ISSUE 10): the 2-stage pipeline run's
            # checkpoints (pipe-structured optimizer state, recorded
            # world=2) resume on a PLAIN 1-way trainer — the restore
            # path unstacks the optimizer layout byte-preserving and
            # the shrink is counted on the wire ---------------------
            s0 = fleet_shrink.value
            gpt_s = Gpt(vocab_size=32, max_len=8, d_model=16,
                        n_layers=2, n_heads=2, d_ff=32, seq_len=8,
                        compute_dtype=None, use_flash=False,
                        seed=9).init_graph()
            tr_s = ShardedTrainer(gpt_s, MeshConfig(data=1))
            ck_s = CheckpointListener(os.path.join(d, "ck"), world=1)
            gpt_s.set_listeners(ck_s)
            loss_s = fleet_resume_fit(
                lambda: tr_s.fit(data_p(), n_epochs=3, resume=True),
                mesh=tr_s.mesh, checkpoint=ck_s, max_restarts=1,
                world=1)
            ck_s.ckpt.close()
            if gpt_s.epoch_count != 3:
                problems.append(f"elastic shrink resume finished "
                                f"{gpt_s.epoch_count}/3 epochs")
            if loss_s is None or not np.isfinite(loss_s):
                problems.append(f"elastic shrink resume loss {loss_s}")
            if fleet_shrink.value - s0 < 1:
                problems.append("2-stage checkpoint resumed on the "
                                "1-way trainer counted no elastic "
                                "shrink")
            ck_p.ckpt.close()
        if counter("fleet_preempt_broadcasts_total").value - fleet_b0 < 1:
            problems.append("fleet_preempt_broadcasts_total did not grow")
        if fleet_resumed.value - fleet_r0 < 1:
            problems.append("fleet_resumes_total did not grow")

    # -- serving fault matrix: zero-downtime KV salvage ----------------
    wd0 = counter("serve_watchdog_restarts_total").value
    salv0 = counter("kv_slots_salvaged_total").value
    drop0 = counter("kv_slots_dropped_total").value
    gpt = Gpt(vocab_size=50, max_len=32, d_model=32, n_layers=2,
              n_heads=4, d_ff=64, seq_len=8, compute_dtype=None,
              seed=3).init_graph()
    offline = TransformerGenerator(gpt)
    p = np.asarray([1, 2, 3, 4], np.int32)

    # one 3-slot server takes both hits in sequence.  tick_batch=1
    # pins the single-tick watchdog deadline this matrix injects
    # against (a fused K-tick scan legitimately stretches the deadline
    # by K and would absorb the stall as a slow scan).  The deadline
    # is ARMED only after the warm submit: the first-dispatch compile
    # runs 1-2s on a loaded box, and a 0.8s deadline live during warm
    # fires a spurious recovery that skews every counter delta the
    # matrix asserts (the watchdog re-reads tick_timeout_s each pass,
    # so tightening it post-warm is race-free).
    with GenerationServer(gpt, n_slots=3, max_len=32, tick_timeout_s=30.0,
                          tick_batch=1,
                          submit_retries=4, retry_backoff_s=0.02) as srv:
        srv.submit(p, n_new=2, timeout=300)          # warm the compiles
        srv.tick_timeout_s = 0.8                     # arm the deadline

        # (1) scheduler crash with three requests mid-decode: the
        # watchdog salvages ALL slots' KV into the rebuilt pool — every
        # caller completes without resubmission, byte-identical
        ref24 = offline.generate(p[None], n_new=24)[0]
        with FaultInjector(SERVE_CRASH_PLAN):
            hs = [srv.submit_async(p, n_new=24) for _ in range(3)]
            for i, h in enumerate(hs):
                try:
                    if not np.array_equal(h.result(timeout=300), ref24):
                        problems.append(
                            f"post-crash salvage output {i} mismatch")
                except Exception as e:
                    problems.append(f"crash-salvaged request {i} "
                                    f"failed: {e}")
        if counter("kv_slots_salvaged_total").value - salv0 != 3:
            problems.append("crash recovery salvaged != 3 slots")
        if counter("kv_slots_dropped_total").value - drop0 != 0:
            problems.append("crash recovery dropped a slot")
        if not srv.healthy():
            problems.append("server not healthy after crash recovery")

        # (2) stuck tick with 2 live + 1 poisoned slot: recovery drops
        # ONLY the poisoned slot (its caller retries through); the two
        # unaffected callers finish offline-identical, un-resubmitted
        salv1 = counter("kv_slots_salvaged_total").value
        drop1 = counter("kv_slots_dropped_total").value
        ref20 = offline.generate(p[None], n_new=20)[0]
        victim_out = {}
        with FaultInjector(SERVE_STALL_PLAN):
            h0 = srv.submit_async(p, n_new=20)
            h1 = srv.submit_async(p, n_new=20)
            vt = threading.Thread(target=lambda: victim_out.update(
                v=srv.submit(p, n_new=20, timeout=300, retries=4)))
            vt.start()                    # third admission -> slot 2
            for _ in range(2000):
                with srv._lock:
                    n_act = len(srv._active)
                if n_act == 3:
                    break
                time.sleep(0.005)
            if n_act != 3:
                problems.append(f"stall scenario admitted {n_act}/3")
            with srv._lock:               # the victim thread's slot is
                victim_slot = [s for s, r in srv._active.items()
                               if r not in (h0, h1)][0]
            if not poison_slot_kv(srv, victim_slot):
                problems.append("could not poison the victim's KV row")
            for i, h in enumerate((h0, h1)):
                try:
                    if not np.array_equal(h.result(timeout=300), ref20):
                        problems.append(
                            f"post-stall salvage output {i} mismatch")
                except Exception as e:
                    problems.append(f"stall-salvaged request {i} "
                                    f"failed: {e}")
            vt.join(timeout=300)
        if not np.array_equal(victim_out.get("v"), ref20):
            problems.append("poisoned slot's retried submit mismatch")
        if counter("kv_slots_salvaged_total").value - salv1 != 2:
            problems.append("stall recovery salvaged != 2 slots")
        if counter("kv_slots_dropped_total").value - drop1 != 1:
            problems.append("stall recovery dropped != 1 slot")
    if counter("serve_watchdog_restarts_total").value - wd0 != 2:
        problems.append("expected exactly 2 watchdog restarts "
                        "(crash + stall)")

    # -- sampled speculative slot salvage (ISSUE 20): the same tick
    # crash against a SAMPLED speculative server.  The watchdog must
    # salvage the slot's target AND draft tables plus the held
    # residual/PRNG state leaves — proven the hard way: the salvaged
    # same-seed sampled stream is BYTE-IDENTICAL to the uncrashed run
    # (fixed K: adaptive depth decisions are host-side and not
    # replayed, so byte pins use a fixed-depth server), and the
    # greedy neighbour in the same mixed pool stays offline-identical.
    spec_salv0 = counter("kv_slots_salvaged_total").value
    spec_wd0 = counter("serve_watchdog_restarts_total").value
    spec_samp = {"temperature": 0.9, "top_k": 6, "seed": 21}
    ref20g = offline.generate(p[None], n_new=20)[0]
    # generous tick_timeout_s: the fault KILLS the scheduler thread
    # (watchdog detects death via is_alive, timeout-independent); a
    # tight stuck-tick deadline would spuriously re-recover during
    # the salvage path's sampled-spec recompiles on a loaded CPU
    with GenerationServer(gpt, n_slots=2, max_len=32,
                          tick_timeout_s=30.0, tick_batch=1,
                          submit_retries=4, retry_backoff_s=0.02,
                          speculative={"k": 2, "rounds": 1,
                                       "draft_layers": 1}) as ssrv:
        ssrv.submit(p, n_new=2, timeout=300)      # warm the compiles
        ref20s = ssrv.submit(p, n_new=20, sampling=dict(spec_samp),
                             timeout=300)         # uncrashed reference
        with FaultInjector(SERVE_SPEC_CRASH_PLAN):
            hg = ssrv.submit_async(p, n_new=20)
            hsamp = ssrv.submit_async(p, n_new=20,
                                      sampling=dict(spec_samp))
            try:
                if not np.array_equal(hg.result(timeout=300), ref20g):
                    problems.append("sampled-spec salvage: greedy "
                                    "neighbour diverged from offline")
                if not np.array_equal(hsamp.result(timeout=300),
                                      ref20s):
                    problems.append(
                        "sampled-spec salvage: same-seed stream not "
                        "byte-identical to the uncrashed run")
            except Exception as e:
                problems.append(f"sampled-spec salvaged request "
                                f"failed: {e}")
        if not ssrv.healthy():
            problems.append("sampled-spec server not healthy after "
                            "salvage")
    if counter("kv_slots_salvaged_total").value - spec_salv0 != 2:
        problems.append("sampled-spec recovery salvaged != 2 slots")
    if counter("serve_watchdog_restarts_total").value - spec_wd0 != 1:
        problems.append("sampled-spec recovery != 1 watchdog restart")

    # -- mesh-sharded replica (ISSUE 17): the same tick crash against
    # a tp=2 server.  The UNCHANGED watchdog salvages every slot's KV
    # into the rebuilt sharded pool — all three callers complete
    # byte-identical, nothing resubmitted — and the mesh-loss flight
    # event lands carrying the slice it spanned.
    tp_ev = registry.counter(
        "flight_events_total",
        labelnames=("kind",)).labels(kind="tp_device_loss")
    ev0 = tp_ev.value
    salv2 = counter("kv_slots_salvaged_total").value
    wd2 = counter("serve_watchdog_restarts_total").value
    with GenerationServer(gpt, n_slots=3, max_len=32,
                          tick_timeout_s=30.0, tick_batch=1,
                          submit_retries=4, retry_backoff_s=0.02,
                          devices=jax.devices()[:2]) as tsrv:
        if tsrv.stats()["tp"] != 2:
            problems.append("mesh chaos server did not build tp=2")
        tsrv.submit(p, n_new=2, timeout=300)     # warm the compiles
        tsrv.tick_timeout_s = 0.8        # arm post-warm (see matrix)
        with FaultInjector(SERVE_TP_CRASH_PLAN):
            hs_t = [tsrv.submit_async(p, n_new=24) for _ in range(3)]
            for i, h in enumerate(hs_t):
                try:
                    if not np.array_equal(h.result(timeout=300),
                                          ref24):
                        problems.append(
                            f"tp=2 crash salvage output {i} mismatch")
                except Exception as e:
                    problems.append(f"tp=2 crash-salvaged request {i} "
                                    f"failed: {e}")
        if not tsrv.healthy():
            problems.append("tp=2 server not healthy after recovery")
    if counter("kv_slots_salvaged_total").value - salv2 != 3:
        problems.append("tp=2 crash recovery salvaged != 3 slots")
    if counter("serve_watchdog_restarts_total").value - wd2 != 1:
        problems.append("tp=2 crash recovery != 1 watchdog restart")
    if tp_ev.value - ev0 < 1:
        problems.append("tp=2 tick crash recorded no tp_device_loss "
                        "flight event")

    # -- serving fleet: SIGKILL-equivalent death of one of two
    # replicas mid-decode.  The seed request warms one replica's
    # prefix cache so affinity routes all four follow-ups there
    # (2 decoding + 2 queued on the victim); the kill migrates every
    # one of them to the survivor, byte-identical to offline decode,
    # with the migrated outcome on the wire.  No FaultInjector here —
    # the fault-count matrix below stays exact. --------------------
    from deeplearning4j_tpu.serving import ServingFleet

    fleet_fam = registry.counter("fleet_requests_total",
                                 labelnames=("tenant", "outcome"))

    def outcome_total(outcome):
        return sum(c.value for vals, c in fleet_fam._items()
                   if vals[1] == outcome)

    mig0 = outcome_total("migrated")
    pf = np.arange(1, 14, dtype=np.int32)
    ref_fleet = offline.generate(pf[None], n_new=12)[0]
    with ServingFleet(gpt, n_replicas=2, n_slots=2, max_len=32,
                      block_size=4, tick_batch=1,
                      tick_timeout_s=None) as fleet:
        h_seed = fleet.submit_async(pf, n_new=2)
        h_seed.result(timeout=300)
        warm = h_seed.replica
        hs = [fleet.submit_async(pf, n_new=12) for _ in range(4)]
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if any(h.emitted > 0 for h in hs):
                break                    # mid-decode on the victim
            time.sleep(0.001)
        fleet.kill(warm)
        for i, h in enumerate(hs):
            try:
                if not np.array_equal(h.result(timeout=300),
                                      ref_fleet):
                    problems.append(
                        f"fleet migrated output {i} mismatch")
            except Exception as e:
                problems.append(f"fleet migrated request {i} "
                                f"failed: {e}")
        if fleet.stats()["healthy_replicas"] != 1:
            problems.append("fleet survivor count != 1 after kill")
        mig_trace = hs[0].trace_id
    if outcome_total("migrated") - mig0 < 1:
        problems.append("fleet kill produced no migrated requests")

    # -- mesh fleet (ISSUE 17): ONE fleet mixing a tp=2 replica and a
    # single-chip replica, the MULTI-CHIP one killed mid-decode —
    # every in-flight request migrates onto the single-chip survivor
    # and completes byte-identical (the sharded and unsharded ticks
    # are the same function by construction, so migrating across
    # topologies is invisible to the caller).  The affinity seed must
    # land on replica 0 (the tp=2 one) for the kill to catch work
    # mid-decode; the scenario retries on a fresh fleet when cold
    # placement sends it elsewhere, or when the short decode outruns
    # the kill and nothing was left to migrate.
    pm = np.arange(3, 16, dtype=np.int32)
    ref_mesh = offline.generate(pm[None], n_new=12)[0]
    migm0 = outcome_total("migrated")
    for attempt in range(3):
        with ServingFleet(gpt, n_replicas=2, n_slots=2, max_len=32,
                          block_size=4, tick_batch=1,
                          tick_timeout_s=None,
                          devices=[jax.devices()[:2], None]) as mflt:
            if mflt.replica(0).stats()["tp"] != 2 \
                    or mflt.replica(1).stats()["tp"] != 1:
                problems.append("mesh fleet replica topology wrong")
            h_seed = mflt.submit_async(pm, n_new=2)
            h_seed.result(timeout=300)
            if h_seed.replica != 0:
                continue             # need the tp=2 replica warm
            hs_m = [mflt.submit_async(pm, n_new=12) for _ in range(4)]
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if any(h.emitted > 0 for h in hs_m):
                    break            # mid-decode on the tp=2 replica
                time.sleep(0.001)
            mflt.kill(0)             # SIGKILL the multi-chip replica
            for i, h in enumerate(hs_m):
                try:
                    if not np.array_equal(h.result(timeout=300),
                                          ref_mesh):
                        problems.append(
                            f"mesh fleet migrated output {i} mismatch")
                except Exception as e:
                    problems.append(f"mesh fleet migrated request {i} "
                                    f"failed: {e}")
            if mflt.stats()["healthy_replicas"] != 1:
                problems.append("mesh fleet survivor count != 1 "
                                "after the tp=2 replica kill")
            if mflt.replica(1).stats()["tp"] != 1:
                problems.append("mesh fleet survivor is not the "
                                "single-chip replica")
        if outcome_total("migrated") - migm0 >= 1:
            break                    # the kill landed mid-decode
    else:
        problems.append("tp=2 replica kill never migrated a request "
                        "(3 attempts)")

    # -- disaggregated prefill/decode (ISSUE 14): kill the PREFILL
    # replica with long-prompt requests staged on it mid-handoff —
    # every request re-places through the EXISTING migration
    # machinery (reclassified direct against the surviving decode
    # replica, since no prefill replica remains) and completes
    # byte-identical to offline generate(); the migrated outcome is
    # asserted on the real scrape at the bottom.  The kill races the
    # (fast) prefill stage, so the scenario retries on a fresh fleet
    # until the kill lands while >= 1 request is still placed on the
    # prefill replica.
    base9 = np.arange(1, 10, dtype=np.int32)
    d_longs = [np.concatenate([base9, np.asarray(
        [i + 1, i + 2, i + 3, i + 4], np.int32)]) for i in range(3)]
    d_refs = [offline.generate(p[None], n_new=8)[0] for p in d_longs]
    migd0 = outcome_total("migrated")
    for attempt in range(3):
        with ServingFleet(gpt, n_replicas=2,
                          roles=("prefill", "decode"), n_slots=2,
                          max_len=32, block_size=4, tick_batch=1,
                          tick_timeout_s=None) as dfleet:
            # one clean round trip first: prefill -> handoff -> decode
            out_d = dfleet.submit(d_longs[0], n_new=8, timeout=300)
            if not np.array_equal(out_d, d_refs[0]):
                problems.append("disagg decode diverged from offline "
                                "generate() pre-kill")
            if dfleet.replica(1).stats()["tier_fetches"] < 1:
                problems.append("disagg handoff restored no blocks on "
                                "the decode replica")
            hs_d = [dfleet.submit_async(p, n_new=8)
                    for p in d_longs[1:]]
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if any(h.replica == 0 for h in hs_d):
                    break            # staged on the prefill replica
                if all(h.done() for h in hs_d):
                    break            # lost the race outright: don't
                                     # burn the deadline, just retry
                time.sleep(0.0005)
            dfleet.kill(0)           # SIGKILL the prefill replica
            for i, h in enumerate(hs_d):
                try:
                    if not np.array_equal(h.result(timeout=300),
                                          d_refs[1 + i]):
                        problems.append(
                            f"disagg migrated output {i} mismatch")
                except Exception as e:
                    problems.append(f"disagg migrated request {i} "
                                    f"failed: {e}")
            if dfleet.stats()["healthy_replicas"] != 1:
                problems.append("disagg fleet survivor count != 1 "
                                "after the prefill-replica kill")
        if outcome_total("migrated") - migd0 >= 1:
            break                    # the kill landed mid-handoff
    else:
        problems.append("prefill-replica kill never migrated a "
                        "request (3 attempts)")

    # -- cross-worker trace store (ISSUE 13): the killed replica's
    # request crossed placements mid-decode — its spans (abandoned
    # victim placement INCLUDED, flushed by the owner-death path)
    # must beacon and stitch into exactly ONE submit -> retire tree,
    # not the disjoint fragments PR 12 left behind ------------------
    with tempfile.TemporaryDirectory() as td:
        telemetry.publish_beacon(
            td, "chaoshost", registry=registry,
            trace_events=telemetry.get_tracer().trace_events())
        fr = telemetry.FleetRegistry(td, stale_after_s=3600.0)
        fr.refresh()
        tree = fr.traces.tree(mig_trace)
    if tree["root"] is None:
        problems.append("kill-mid-decode trace has no stitched root "
                        f"(trace {mig_trace})")
    else:
        def _count(node, name):
            return ((node["name"] == name)
                    + sum(_count(c, name) for c in node["children"]))
        if tree["orphans"]:
            problems.append(
                "kill-mid-decode trace left orphan fragments: "
                f"{[n['name'] for n in tree['orphans']]}")
        if _count(tree["root"], "request/placement") < 2:
            problems.append(
                "migrated request's tree holds < 2 placement spans "
                "(victim + failover) — the recovery fragment was "
                "lost")

    # -- closed-loop autoscaler (ISSUE 12 + 13): the step load on a
    # 1-replica fleet must now scale 1 -> 2 PREDICTIVELY — the
    # backlog jump's growth rate projects a queue_depth_high breach
    # inside the horizon and pre-warms the replica while every
    # reactive signal is still quiet (the 1s wait target CANNOT have
    # tripped before 1s of queueing even existed; the forecast fires
    # within the first few 0.05s evaluations) — then back 2 -> 1 once
    # the load drains, with ZERO interactive deadline misses.
    # Asserted from the real scrape at the bottom: the pre-warm
    # counter only increments when the up action's reasons were
    # forecast-ONLY, so prewarms >= 1 IS "replica added before the
    # reactive breach signal".
    from deeplearning4j_tpu.serving import AutoscalePolicy, Autoscaler
    as_actions = registry.counter("fleet_autoscale_actions_total",
                                  labelnames=("direction",))
    prewarms = registry.counter("fleet_autoscale_prewarms_total")
    up0 = as_actions.labels(direction="up").value
    down0 = as_actions.labels(direction="down").value
    pw0 = prewarms.value
    fleet2 = ServingFleet(gpt, n_replicas=1, n_slots=2, max_len=32,
                          block_size=4, tick_batch=1,
                          tick_timeout_s=None)
    pol = AutoscalePolicy(min_replicas=1, max_replicas=2,
                          queue_wait_p99_target_s=1.0,
                          queue_depth_high=64,
                          forecast_horizon_s=60.0,
                          forecast_window_s=2.0,
                          forecast_min_points=3,
                          up_consecutive=2, down_consecutive=4,
                          cooldown_s=0.3)
    scaler = Autoscaler(fleet2, pol, interval_s=0.05,
                        tenant_classes={"analytics": "batch"}).start()
    try:
        pa = np.asarray([1, 2, 3, 4], np.int32)
        fleet2.submit(pa, n_new=2, tenant="inter", timeout=300)
        hs2 = [fleet2.submit_async(pa, n_new=24, tenant="inter",
                                   deadline_s=300.0)
               for _ in range(40)]
        for i, h in enumerate(hs2):
            try:
                h.result(timeout=300)
            except Exception as e:
                problems.append(f"step-load request {i} failed: {e}")
        drain_by = time.monotonic() + 120
        while time.monotonic() < drain_by and scaler.target > 1:
            time.sleep(0.05)
    finally:
        scaler.close()
    if as_actions.labels(direction="up").value - up0 < 1:
        problems.append("step load did not autoscale 1 -> 2")
    if prewarms.value - pw0 < 1:
        problems.append(
            "step load scaled up REACTIVELY — the forecast did not "
            "pre-warm the replica before an SLO signal tripped")
    if as_actions.labels(direction="down").value - down0 < 1:
        problems.append("drained fleet did not autoscale 2 -> 1")
    if scaler.target != 1:
        problems.append(f"autoscaler target settled at {scaler.target}"
                        " != 1")
    if fleet2.stats()["healthy_replicas"] != 1:
        problems.append("fleet healthy_replicas != 1 after scale-in")
    fleet2.shutdown(drain=True)

    # -- SLO error-budget closed loop + kill under pressure (ISSUE
    # 15): induced overload -> the burn-rate alert fires on the
    # AGGREGATED scrape BEFORE any interactive deadline miss -> the
    # autoscaler pre-warm is attributed to the ALERT signal
    # (fleet_autoscale_alert_prewarms_total) -> a replica SIGKILL
    # mid-storm yields EXACTLY ONE postmortem bundle whose merged
    # timeline (scripts/postmortem.py) holds the victim's final
    # dispatch events, its open spans and the alert state. --------
    from deeplearning4j_tpu.telemetry import flightrec
    from deeplearning4j_tpu.telemetry.slo import AlertEngine, SLOSpec

    def _load_postmortem():
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "postmortem.py")
        spec = importlib.util.spec_from_file_location("postmortem",
                                                      path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    alert_prewarms = counter("fleet_autoscale_alert_prewarms_total")
    apw0 = alert_prewarms.value
    exp0 = outcome_total("expired")
    pa = np.asarray([1, 2, 3, 4], np.int32)
    ref_slo = offline.generate(pa[None], n_new=24)[0]
    slo_dir = tempfile.mkdtemp(prefix="chaos_slo_")
    # the queue-phase latency SLO: waits past 0.1s are budget burn —
    # under the storm they appear SECONDS before any 300s deadline
    # could possibly miss, so the alert firing IS the early signal
    slo_eng = AlertEngine(
        [SLOSpec("inter-latency", objective="latency", target=0.9,
                 phase="queue", threshold_s=0.1, window_s=600.0,
                 windows=[(0.4, 1.2, 1.5, "page")])])
    # the burning SLO reads the queue-phase latency series — by now
    # the fleet/disagg/step-load scenarios have been feeding it for
    # minutes, so this top-up is normally a no-op guard; it only
    # sleeps when the preceding scenarios ran implausibly fast
    def _queue_series_span():
        spans = [tsdb.span(k) for k in tsdb.series()
                 if k.startswith("fleet_request_phase_seconds")
                 and 'phase="queue"' in k]
        return max(spans, default=0.0)

    history_by = time.monotonic() + min_history_s + 30.0
    while (_queue_series_span() < min_history_s
           and time.monotonic() < history_by):
        time.sleep(0.25)
    if _queue_series_span() < min_history_s:
        problems.append(
            f"queue-phase series never reached {min_history_s:g}s of "
            f"recorded history (got {_queue_series_span():.1f}s)")
    recorder = telemetry.get_flight_recorder()
    recorder.install_dump(slo_dir, host="chaos", alerts=slo_eng)
    fleet3 = ServingFleet(gpt, n_replicas=1, n_slots=2, max_len=32,
                          block_size=4, tick_batch=1,
                          tick_timeout_s=None)
    # reactive targets deliberately untrippable (30s wait target, no
    # depth ceiling, no forecaster): ONLY the burn-rate alert can
    # drive the scale-up, so the pre-warm attribution is airtight
    pol3 = AutoscalePolicy(min_replicas=1, max_replicas=2,
                           queue_wait_p99_target_s=30.0,
                           up_consecutive=2, down_consecutive=1000,
                           cooldown_s=0.3)
    scaler3 = Autoscaler(fleet3, pol3, interval_s=0.05,
                         alert_engine=slo_eng).start()
    try:
        # enough backlog that the storm outlasts the engine's 1.2s
        # long-window coverage on a fast box
        hs3 = [fleet3.submit_async(pa, n_new=24, tenant="inter",
                                   deadline_s=300.0)
               for _ in range(64)]
        fire_by = time.monotonic() + 120
        while time.monotonic() < fire_by:
            if alert_prewarms.value - apw0 >= 1:
                break
            time.sleep(0.02)
        if alert_prewarms.value - apw0 < 1:
            problems.append(
                "induced overload produced no ALERT-attributed "
                f"pre-warm (alerts: {slo_eng.alerts()})")
        if outcome_total("expired") - exp0 != 0:
            problems.append("an interactive deadline miss preceded "
                            "the burn-rate alert pre-warm")
        if all(h.done() for h in hs3):
            problems.append("storm drained before the kill — no "
                            "in-flight forensics to freeze")
        # SIGKILL the storm's original replica mid-decode,
        # IMMEDIATELY after the pre-warm: the kill freezes the black
        # box while its requests' spans are still open, then
        # everything migrates to the pre-warmed replica
        fleet3.kill(0)
        # the alert's lifecycle must be observable on the AGGREGATED
        # scrape (the engine's families beacon like any other; the
        # transitions counter is monotonic, so the observation is
        # race-free even after the burn resolves)
        telemetry.publish_beacon(slo_dir, "chaos", registry=registry)
        # the aggregated view serves the PROCESS store at /query —
        # the burn the engine decided on must be reproducible from
        # the recorded history over HTTP (ISSUE 16)
        fr3 = telemetry.FleetRegistry(slo_dir, stale_after_s=3600.0,
                                      tsdb=tsdb)
        with telemetry.start_metrics_server(fr3, port=0) as srv3:
            agg_body = urllib.request.urlopen(
                f"http://127.0.0.1:{srv3.port}/metrics",
                timeout=5).read().decode()
            fired = [a for a in slo_eng.alerts()
                     if a["slo"] == "inter-latency"
                     and a.get("t_fired") is not None]
            if not fired:
                problems.append("no fired inter-latency alert to "
                                "check the /query burn window against")
            else:
                wall_fired = time.time() - (time.monotonic()
                                            - fired[0]["t_fired"])
                qdoc = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{srv3.port}/query?"
                    "series=fleet_slo_burn_rate&slo=inter-latency&"
                    f"window=1.2s&start={wall_fired - 5.0}&"
                    f"end={time.time() + 1.0}",
                    timeout=5).read().decode())
                burns = [p[1] for r in qdoc.get("results", ())
                         for p in r.get("points", ())]
                if not burns:
                    problems.append(
                        "/query returned no burn-rate history over "
                        f"the firing window ({qdoc})")
                elif max(burns) < 1.5:
                    problems.append(
                        "/query burn-rate history never reached the "
                        f"1.5 firing threshold (max {max(burns):.3g})"
                        " — inconsistent with the engine's decision")
        for needle in ('fleet_slo_alert_transitions_total'
                       '{slo="inter-latency",to="firing",'
                       'host="chaos"}',
                       'fleet_slo_alert_firing{slo="inter-latency",'
                       'host="chaos"}',
                       'fleet_autoscale_alert_prewarms_total'
                       '{host="chaos"}'):
            if needle not in agg_body:
                problems.append(f"aggregated scrape missing {needle}")
        for i, h in enumerate(hs3):
            try:
                if not np.array_equal(h.result(timeout=300), ref_slo):
                    problems.append(f"slo-storm output {i} mismatch "
                                    "after the kill")
            except Exception as e:
                problems.append(f"slo-storm request {i} failed after "
                                f"the kill: {e}")
    finally:
        scaler3.close()
        fleet3.shutdown(drain=True)
        recorder.uninstall_dump()
    if outcome_total("expired") - exp0 != 0:
        problems.append("interactive deadline misses during the SLO "
                        "kill storm")
    bundles = flightrec.list_bundles(slo_dir)
    if len(bundles) != 1:
        problems.append(f"expected exactly 1 postmortem bundle, "
                        f"found {len(bundles)}")
    else:
        # merged timeline: the victim's final dispatch events, its
        # open spans at the kill, and the alert state — stitched
        # against the beaconed trace store
        telemetry.publish_beacon(
            slo_dir, "chaos", registry=registry,
            trace_events=telemetry.get_tracer().trace_events())
        pm = _load_postmortem()
        bdoc = flightrec.load_bundle(bundles[0])
        entries = pm.merge_timeline(bdoc,
                                    pm.build_trace_store(slo_dir))
        if bdoc.get("reason") != "chaos_kill: replica 0":
            problems.append(f"bundle reason {bdoc.get('reason')!r}")
        if not any(e["src"] == "event" and e["what"] == "dispatch"
                   and "replica=0" in e["detail"] for e in entries):
            problems.append("postmortem timeline lost the victim's "
                            "final dispatch events")
        if not any(e["src"] == "open" for e in entries):
            problems.append("postmortem timeline holds no open spans "
                            "(the in-flight work at the kill)")
        if not any(e["src"] == "alert"
                   and e["what"] == "slo:inter-latency"
                   for e in entries):
            problems.append("postmortem timeline lost the alert "
                            "state")
        if not any(e["src"] == "span" for e in entries):
            problems.append("postmortem timeline stitched no trace-"
                            "store spans")
        # ISSUE 16: the bundle carries the victim's pre-crash metric
        # history, and the burning SLO's underlying series spans the
        # required window into the kill
        hist = (bdoc.get("history") or {}).get("series") or {}
        qspans = [pts[-1][0] - pts[0][0]
                  for k, ent in hist.items()
                  if k.startswith("fleet_request_phase_seconds")
                  and 'phase="queue"' in k
                  for pts in [ent.get("points") or []] if len(pts) > 1]
        # dump_recent keeps the last 300s; the assert floor is the
        # smaller of that and min_history_s, minus sampling slack
        floor = min(min_history_s, 300.0) - 5.0
        if not qspans:
            problems.append("bundle history holds no queue-phase "
                            "series (the burning SLO's source)")
        elif max(qspans) < floor:
            problems.append(
                f"bundle history for the queue-phase series spans "
                f"{max(qspans):.1f}s < {floor:.1f}s pre-crash")
        if not pm.render_history(bdoc):
            problems.append("postmortem render_history produced "
                            "nothing for a bundle with history")
    shutil.rmtree(slo_dir, ignore_errors=True)

    # -- production front door (ISSUE 18): a REAL overload storm, no
    # FaultInjector (the fault-count matrix below stays exact).  An
    # all-bad batch tenant aged past the long burn window drives the
    # engine's admission projection; the attached ladder walks a
    # 2-replica fleet to the shed rung — the batch tenant is REJECTED
    # with a server-advised retry-after, interactive budgets are
    # capped — holds there long enough for the 1s TSDB recorder to
    # witness the elevated rung, then walks back to rung 0 once the
    # burn clears.  Interactive traffic rides straight through with
    # ZERO deadline misses, a near-deadline request races a hedge on
    # the second replica (first completion wins, the loser is always
    # cancelled), and the whole ladder walk is REPLAYED from the
    # recorded history over /query (ISSUE 16). ---------------------
    from deeplearning4j_tpu.serving import (AdmissionRejectedError,
                                            DegradeLadder, TenantQuota)

    dreg = telemetry.MetricsRegistry()
    dfam = dreg.counter("fleet_requests_total",
                        labelnames=("tenant", "outcome"))
    deg_eng = AlertEngine(
        [SLOSpec("smoke-degrade", target=0.9, tenant="bulk",
                 window_s=600.0, windows=[(0.1, 0.3, 1.5, "page")])],
        source=dreg, registry=telemetry.MetricsRegistry())
    deg_eng.evaluate(now=0.0)            # prime the history
    for t in (0.2, 0.4, 0.6):            # 100% bad, past the 0.3s
        dfam.labels(tenant="bulk", outcome="failed").inc(5)
        deg_eng.evaluate(now=t)          # long window: burn 10x
    exp_d0 = outcome_total("expired")
    hlaunch = counter("fleet_hedges_launched_total")
    hcancel = counter("fleet_hedges_cancelled_total")
    hl0, hc0 = hlaunch.value, hcancel.value
    pd_ = np.asarray([2, 3, 5, 7], np.int32)
    ref_deg = offline.generate(pd_[None], n_new=2)[0]
    ref_full = offline.generate(pd_[None], n_new=8)[0]
    wall_deg0 = time.time()
    with ServingFleet(gpt, n_replicas=2, n_slots=2, max_len=32,
                      block_size=4, tick_batch=1, tick_timeout_s=None,
                      hedge_slack_s=60.0,
                      quotas={"bulk": TenantQuota(klass="batch")}
                      ) as dfleet:
        lad = DegradeLadder(dfleet, deg_eng,
                            thresholds=(1.0, 2.0, 3.0, 4.0, 5.0),
                            hold_down_s=0.0)
        dfleet.attach_degrade(lad)
        rung_hi = lad.evaluate(now=0.6)  # real projection read
        if rung_hi < 2:
            problems.append(f"induced 10x burn drove the ladder to "
                            f"rung {rung_hi}, expected >= 2")
        try:
            dfleet.submit_async(np.asarray([1, 2, 3], np.int32), 4,
                                tenant="bulk")
            problems.append("batch tenant admitted during the "
                            "overload storm (shed rung must reject)")
        except AdmissionRejectedError as e:
            if not e.retry_after_s > 0:
                problems.append("shed batch tenant carried no "
                                "retry_after_s hint")
        # the interactive storm rides THROUGH the overload: degraded
        # (n_new capped 8 -> 2, greedy forced) but never rejected and
        # never expired, and the capped outputs stay byte-identical
        # to the offline prefix
        hds = [dfleet.submit_async(pd_, n_new=8, tenant="chat",
                                   deadline_s=300.0)
               for _ in range(6)]
        # hold the rung while the 1s-cadence recorder samples it: the
        # /query replay below reads the RECORDED walk, so at least
        # one beacon tick must witness the elevated rung
        time.sleep(2.2)
        for i, h in enumerate(hds):
            try:
                if not np.array_equal(h.result(timeout=300), ref_deg):
                    problems.append(
                        f"degraded storm output {i} not "
                        "byte-identical to the capped offline prefix")
            except Exception as e:
                problems.append(f"degraded storm request {i} failed "
                                f"during the overload: {e}")
        for i in range(12):              # the burn cleared: walk down
            rung = lad.evaluate(now=10.0 + i)
            if rung == 0:
                break
        if rung != 0:
            problems.append("ladder did not walk back to rung 0 "
                            "after the burn cleared")
        if not np.array_equal(
                dfleet.submit(pd_, n_new=8, tenant="chat",
                              timeout=300), ref_full):
            problems.append("post-recovery request still degraded "
                            "(output not byte-identical to offline)")
        # near-deadline interactive request: the front door hedges it
        # onto the second warm replica — first completion wins, and
        # once the race resolves launched == cancelled exactly
        hh = dfleet.submit_async(pd_, n_new=8, tenant="chat",
                                 deadline_s=30.0)
        if not np.array_equal(hh.result(timeout=300), ref_full):
            problems.append("hedged request output mismatch")
        hedge_by = time.monotonic() + 30
        while time.monotonic() < hedge_by:
            if (hlaunch.value - hl0 >= 1
                    and hcancel.value - hc0 == hlaunch.value - hl0):
                break
            time.sleep(0.01)
        if hlaunch.value - hl0 < 1:
            problems.append("near-deadline request launched no hedge")
        elif hcancel.value - hc0 != hlaunch.value - hl0:
            problems.append(
                "hedge race left unresolved: launched "
                f"{hlaunch.value - hl0} != cancelled "
                f"{hcancel.value - hc0}")
        # let the recorder witness the recovered rung before the
        # replay reads the history
        time.sleep(1.3)
    if outcome_total("expired") - exp_d0 != 0:
        problems.append("interactive deadline misses during the "
                        "overload storm")
    # replay the ladder walk from the RECORDED history over /query:
    # the rung the storm reached and the recovery to 0 must both be
    # reproducible from the wire, not just from in-process state
    deg_dir = tempfile.mkdtemp(prefix="chaos_degrade_")
    telemetry.publish_beacon(deg_dir, "chaos", registry=registry)
    frd = telemetry.FleetRegistry(deg_dir, stale_after_s=3600.0,
                                  tsdb=tsdb)
    with telemetry.start_metrics_server(frd, port=0) as dsrv:
        qdoc = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{dsrv.port}/query?"
            f"series=fleet_degrade_rung&start={wall_deg0 - 2.0}&"
            f"end={time.time() + 1.0}", timeout=5).read().decode())
        rungs = [p[1] for r in qdoc.get("results", ())
                 for p in r.get("points", ())]
        if not rungs:
            problems.append("/query returned no fleet_degrade_rung "
                            f"history over the storm window ({qdoc})")
        else:
            if max(rungs) < 2:
                problems.append(
                    "recorded ladder walk never reached rung 2 (max "
                    f"{max(rungs):.0f}) — inconsistent with the shed "
                    "the storm observed")
            if rungs[-1] != 0:
                problems.append(
                    "recorded ladder walk did not return to rung 0 "
                    f"(last sample {rungs[-1]:.0f})")
    shutil.rmtree(deg_dir, ignore_errors=True)

    # -- sanitizer: one deliberate nan trip so the series has a
    # labeled child on the wire (check_finite itself is unconditional
    # — DL4J_TPU_SANITIZE gates the CALL SITES, not the check) -------
    from deeplearning4j_tpu.analysis import SanitizerError, sanitize
    try:
        sanitize.check_finite("chaos/probe", float("nan"))
        problems.append("sanitizer did not trip on NaN")
    except SanitizerError:
        pass

    # -- static analysis: lint series on the wire ----------------------
    ct.emit_analysis_series(problems)
    # the LIVE configuration's lock-order graph (fleet + ladder +
    # autoscaler + alert + tsdb threads) must be acyclic — a CONC301
    # cycle is a latent deadlock and fails the chaos run outright
    ct.assert_live_lock_order(problems, cache_path=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".dl4j_lint_cache.json"))

    # -- every kind fired (preempt thrice: matrix + bit-identical run
    # + pipeline fleet run; every scheduled serve stall throttled a
    # scheduler pass) --
    expected = {k: 1 for k in resilience.FAULT_KINDS}
    expected["preempt"] = 3
    all_serve_plans = (SERVE_CRASH_PLAN + SERVE_STALL_PLAN
                       + SERVE_TP_CRASH_PLAN + SERVE_SPEC_CRASH_PLAN)
    expected["serve_tick_stall"] = sum(
        s.startswith("serve_tick_stall") for s in all_serve_plans)
    expected["serve_tick_fail"] = sum(
        s.startswith("serve_tick_fail") for s in all_serve_plans)
    for k in resilience.FAULT_KINDS:
        delta = fault_counter.labels(kind=k).value - faults_before[k]
        if delta != expected[k]:
            problems.append(f"faults_injected_total{{kind={k}}} grew "
                            f"{delta} != {expected[k]}")

    # -- scrape: the recovery series are on the wire -------------------
    body = ct.scrape_body(telemetry, registry)
    required = list(ct.RESILIENCE_SERIES)
    required += [f'faults_injected_total{{kind="{k}"}}'
                 for k in resilience.FAULT_KINDS]
    required += ["retry_attempts_bucket", "retry_backoff_seconds_bucket"]
    required += ["lint_lock_graph_cycles"]
    # the fleet/salvage counters must carry the REAL recovery values on
    # the wire, not just exist
    for needle in ("fleet_preempt_broadcasts_total",
                   'fleet_resumes_total{outcome="resumed"}',
                   'fleet_elastic_resumes_total{direction="shrink"}',
                   "kv_slots_salvaged_total",
                   # disagg handoff (ISSUE 14): the prefill->decode
                   # block transfer + the decode-side tier restore
                   # must carry real values after the disagg scenario
                   "kv_handoff_blocks_total",
                   "kv_tier_fetches_total",
                   "serve_watchdog_restarts_total",
                   # the step-load scenario's autoscale actions, both
                   # directions, on the wire (ISSUE 12)
                   'fleet_autoscale_actions_total{direction="up"}',
                   'fleet_autoscale_actions_total{direction="down"}',
                   # the predictive pre-warm that beat the reactive
                   # signals to the scale-up (ISSUE 13)
                   "fleet_autoscale_prewarms_total",
                   # the ALERT-attributed pre-warm + the bundle the
                   # SLO kill storm published (ISSUE 15)
                   "fleet_autoscale_alert_prewarms_total",
                   "postmortem_bundles_total"):
        for line in body.splitlines():
            if line.startswith(needle + " "):
                if float(line.rsplit(" ", 1)[1]) <= 0:
                    problems.append(f"{needle} scraped as 0 after "
                                    "recoveries ran")
                break
        else:
            problems.append(f"{needle} missing from the scrape")
    # the fleet migration outcome must carry a REAL value on the wire
    for line in body.splitlines():
        if (line.startswith("fleet_requests_total{")
                and 'outcome="migrated"' in line
                and float(line.rsplit(" ", 1)[1]) > 0):
            break
    else:
        problems.append('fleet_requests_total{outcome="migrated"} '
                        "missing or 0 on the scrape after a replica "
                        "kill")
    # ZERO interactive deadline misses through the 1->2->1 step load:
    # the expired outcome for the interactive tenant must be absent
    # (never minted) or scrape as 0
    for line in body.splitlines():
        if (line.startswith("fleet_requests_total{")
                and 'tenant="inter"' in line
                and 'outcome="expired"' in line
                and float(line.rsplit(" ", 1)[1]) > 0):
            problems.append(
                "interactive tenant missed deadlines during the "
                f"autoscale step load: {line}")
    required += ct.ANALYSIS_SERIES
    # ISSUE 18: the overload storm's admission outcomes, ladder rung,
    # hedge race counters and degrade/hedge flight events on the wire
    required += ct.DEGRADE_SERIES
    required += ['sanitizer_trips_total{mode="nan"}']
    # ISSUE 13: the prediction gauges the step-load scenario drove,
    # and the optimizer-step device-phase samples the pipeline chaos
    # run's ShardedTrainer folded in
    required += [
        'fleet_autoscale_forecast{signal="firing"}',
        'fleet_autoscale_forecast{signal="breach_s"}',
        'fleet_device_phase_seconds_bucket{device="cpu:0",'
        'phase="optimizer_step"',
        # ISSUE 15: the burn-rate alert's lifecycle on the wire, and
        # the flight-recorder events the scenarios fed
        'fleet_slo_alert_transitions_total{slo="inter-latency",'
        'to="firing"}',
        'fleet_slo_alert_firing{slo="inter-latency"}',
        'fleet_slo_error_budget_remaining{slo="inter-latency"}',
        'flight_events_total{kind="dispatch"}',
        # ISSUE 17: the mesh-loss event the tp=2 tick crash recorded
        'flight_events_total{kind="tp_device_loss"}',
        'flight_events_total{kind="chaos_kill"}',
        'flight_events_total{kind="scale"}',
        'flight_events_total{kind="watchdog"}',
    ]
    problems += ct.missing_series(body, required)

    tsdb.close()
    print(json.dumps({"ok": not problems, "problems": problems}))
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
