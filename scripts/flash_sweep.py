#!/usr/bin/env python
"""Flash-vs-XLA crossover sweep: measure fwd+bwd attention time over
d in {64,128}, t in {256,512,1024,2048}, with and without bias /
causal, on the real chip — plus a block-size sweep at the causal
flagship shape.  Writes FLASH_SWEEP_r05.json; the routing table in
kernels/flash_attention.py is derived from this artifact.

Protocol (r5, replaces the r4 harness whose plain-variant rows were
tunnel artifacts): DIFFERENTIAL TWO-SCAN-LENGTH timing.  Each config
runs the kernel inside a single jitted ``lax.scan`` over rotating
buffers at two scan lengths (8 and 72 iterations; configs measuring
under 1.5 ms re-measure at 8 and 200 so the signal dominates tunnel
jitter, and a non-positive differential is an error, not a number)
with a seed-perturbed input (defeats the runtime result cache) and a
scalar readback (forces the async tunnel to flush —
``block_until_ready`` alone does not).  Per-iteration time =
(T_long - T_short) / (n_long - n_short), which cancels
every fixed cost: per-call tunnel RTT (~5 ms), dispatch, readback
(~70 ms), and first-call poison.  The r4 harness timed bare per-call
loops, so every number was floored at the tunnel RTT and the first
config measured after buffer allocation (always the plain variant)
absorbed the transfer poison — hence the bogus flat ~50 ms plain rows.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

_SEED = [0]


def _wall(run, args, repeats=3):
    import jax.numpy as jnp
    best = 1e9
    for _ in range(repeats):
        _SEED[0] += 1
        t0 = time.perf_counter()
        _ = float(run(*args, 1e-6 * _SEED[0]))   # readback flushes
        best = min(best, time.perf_counter() - t0)
    return best


def measure(step_fn, bufs, n1=8, n2=72):
    """step_fn(q, k, v) -> scalar; bufs = (qs, ks, vs) each [4, ...].
    Returns ms/iteration via the differential protocol."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def make_run(n_iter):
        @jax.jit
        def run(qs, ks, vs, seed):
            qs = qs + seed
            def body(c, i):
                return c + step_fn(qs[i % 4], ks[i % 4], vs[i % 4]), None
            c, _ = lax.scan(body, 0.0, jnp.arange(n_iter))
            return c
        return run

    r1, r2 = make_run(n1), make_run(n2)
    _SEED[0] += 1
    _ = float(r1(*bufs, 1e-6 * _SEED[0]))        # compile
    _SEED[0] += 1
    _ = float(r2(*bufs, 1e-6 * _SEED[0]))
    ms = (_wall(r2, bufs) - _wall(r1, bufs)) / (n2 - n1) * 1e3
    if ms < 1.5 and n2 <= 72:
        # sub-1.5 ms/iter: the 64-iteration difference (~100 ms) is the
        # same order as the tunnel's call-to-call jitter — stretch to a
        # 192-iteration difference so the signal dominates
        return measure(step_fn, bufs, n1=8, n2=200)
    if ms <= 0:
        # a negative differential is a failed measurement, never a
        # time — refuse to record it (r4's harness silently accepted
        # these and they ended up in the routing artifact)
        raise RuntimeError(
            f"non-positive differential ({ms:.3f} ms) at n2={n2}; "
            "tunnel jitter swamped the signal")
    return ms


def main():
    import jax
    import jax.numpy as jnp
    import deeplearning4j_tpu.kernels  # noqa: F401  (registers module)
    fa = sys.modules["deeplearning4j_tpu.kernels.flash_attention"]

    assert jax.default_backend() == "tpu", "sweep needs the real chip"
    rng = np.random.default_rng(0)
    rows = []
    BATCH_FOR_T = {256: 64, 512: 32, 1024: 16, 2048: 8}

    def grad_of(f):
        # all three cotangents: flash's custom_vjp always computes
        # dq/dk/dv, so differentiating only argnums=0 would let XLA
        # DCE its dK/dV matmuls and skew the comparison against flash
        def step(q, k, v):
            dq, dk, dv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
            return (jnp.sum(dq.astype(jnp.float32))
                    + jnp.sum(dk.astype(jnp.float32))
                    + jnp.sum(dv.astype(jnp.float32)))
        return step

    for d in (64, 128):
        h = 12 if d == 64 else 6
        for t in (256, 512, 1024, 2048):
            b = BATCH_FOR_T[t]
            mk = lambda: jnp.asarray(
                rng.normal(size=(4, b, h, t, d)), jnp.bfloat16)
            bufs = (mk(), mk(), mk())
            bias = jnp.zeros((b, 1, 1, t), jnp.float32)
            for causal in (False, True):
                for use_bias in (False, True):
                    bi = bias if use_bias else None
                    blocks = fa._auto_blocks(t, causal=causal)

                    def fl(q, k, v, _bl=blocks, _bi=bi, _c=causal):
                        return jnp.sum(fa.flash_attention(
                            q, k, v, *_bl, bias=_bi,
                            causal=_c).astype(jnp.float32))

                    def xl(q, k, v, _bi=bi, _c=causal):
                        return jnp.sum(fa.xla_attention(
                            q, k, v, bias=_bi,
                            causal=_c).astype(jnp.float32))

                    try:
                        t_fl = measure(grad_of(fl), bufs)
                    except Exception:
                        t_fl = None
                    try:
                        t_xl = measure(grad_of(xl), bufs)
                    except Exception:
                        t_xl = None
                    ok = t_fl is not None and t_xl is not None
                    rows.append({
                        "d": d, "h": h, "t": t, "b": b,
                        "causal": causal, "bias": use_bias,
                        "blocks": list(blocks),
                        "flash_ms": (None if t_fl is None
                                     else round(t_fl, 3)),
                        "xla_ms": (None if t_xl is None
                                   else round(t_xl, 3)),
                        "flash_speedup": (round(t_xl / t_fl, 3)
                                          if ok else None)})
                    print(json.dumps(rows[-1]), flush=True)

    # block sweep at the causal flagship shape (t=2048, d=128)
    b, h, t, d = 8, 6, 2048, 128
    mk = lambda: jnp.asarray(rng.normal(size=(4, b, h, t, d)),
                             jnp.bfloat16)
    bufs = (mk(), mk(), mk())
    blocks = []
    for bq in (256, 512, 1024):
        for bk in (256, 512, 1024):
            if t % bq or t % bk:
                continue
            try:
                def f(q, k, v, _bq=bq, _bk=bk):
                    return jnp.sum(fa.flash_attention(
                        q, k, v, _bq, _bk,
                        causal=True).astype(jnp.float32))
                ms = measure(grad_of(f), bufs)
                blocks.append({"blk_q": bq, "blk_k": bk,
                               "ms": round(ms, 3)})
                print(json.dumps(blocks[-1]), flush=True)
            except Exception as e:
                blocks.append({"blk_q": bq, "blk_k": bk,
                               "error": str(e)[:120]})

    # bthd layout at the flagship shape: the kernels read [b, t, h, d]
    # in place (production path for d=128 models) — vs the transposed
    # bhtd call.  Same data as the block sweep, re-viewed (buffers are
    # [4, b, h, t, d]; one device-side transpose).
    bufs4 = tuple(x.swapaxes(2, 3) for x in bufs)
    blocks_flag = fa._auto_blocks(t, causal=True)
    bthd_rows = []
    for lay in ("bthd", "bhtd"):
        def f(q, k, v, _l=lay, _bl=blocks_flag):
            if _l == "bhtd":
                q, k, v = (x.swapaxes(1, 2) for x in (q, k, v))
            return jnp.sum(fa.flash_attention(
                q, k, v, *_bl, causal=True,
                layout=_l).astype(jnp.float32))
        try:
            ms = measure(grad_of(f), bufs4)
            bthd_rows.append({"layout": lay, "blocks": list(blocks_flag),
                              "note": ("in-place [b,t,h,d]" if
                                       lay == "bthd" else
                                       "transpose + flat kernel"),
                              "ms": round(ms, 3)})
            print(json.dumps(bthd_rows[-1]), flush=True)
        except Exception as e:
            bthd_rows.append({"layout": lay, "error": str(e)[:120]})

    out = {"rows": rows, "causal_t2048_block_sweep": blocks,
           "bthd_flagship_causal_fwd_bwd": bthd_rows,
           "protocol": "fwd+bwd sum(dq)+sum(dk)+sum(dv) grad-of-sum "
                       "(argnums 0,1,2 — symmetric work for flash's "
                       "custom_vjp vs XLA autodiff) inside one jitted "
                       "lax.scan over 4 rotating seed-perturbed "
                       "buffers; per-iter ms = (T(scan 72) - "
                       "T(scan 8)) / 64, re-measured at (200-8) when "
                       "under 1.5 ms, best of 3, scalar-readback "
                       "flush; non-positive differentials error out "
                       "rather than record — fixed tunnel costs "
                       "(RTT/dispatch/readback/poison) cancel in the "
                       "difference"}
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "FLASH_SWEEP_r05.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print("wrote", path)


if __name__ == "__main__":
    main()
