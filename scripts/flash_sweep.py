#!/usr/bin/env python
"""Flash-vs-XLA crossover sweep (VERDICT r3 item 10): measure fwd+bwd
attention time over d in {64,128}, t in {256,512,1024,2048}, with and
without bias / causal, on the real chip — plus a block-size sweep at
the causal flagship shape.  Writes FLASH_SWEEP_r04.json; the routing
table in kernels/flash_attention.py is derived from this artifact.

Protocol: rotate 4 input buffers, 30 timed iters, end with a scalar
readback; one throwaway warm-up run per config (first-run timings
through the axon tunnel are poisoned — see bench.py header).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def timed(fn, args_list, iters=30):
    import jax
    import jax.numpy as jnp
    out = fn(*args_list[0])
    jax.block_until_ready(out)
    for a in args_list:         # warm every buffer's executable path
        out = fn(*a)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for i in range(iters):
        out = fn(*args_list[i % len(args_list)])
    _ = float(jnp.sum(out[0].astype(jnp.float32)))
    return (time.perf_counter() - t0) / iters * 1e3


def main():
    import jax
    import jax.numpy as jnp
    import deeplearning4j_tpu.kernels  # noqa: F401  (registers module)
    fa = sys.modules["deeplearning4j_tpu.kernels.flash_attention"]

    assert jax.default_backend() == "tpu", "sweep needs the real chip"
    rng = np.random.default_rng(0)
    rows = []
    BATCH_FOR_T = {256: 64, 512: 32, 1024: 16, 2048: 8}
    for d in (64, 128):
        h = 12 if d == 64 else 6
        for t in (256, 512, 1024, 2048):
            b = BATCH_FOR_T[t]
            mk = lambda: jnp.asarray(
                rng.normal(size=(b, h, t, d)), jnp.bfloat16)
            bufs = [(mk(), mk(), mk()) for _ in range(4)]
            bias = jnp.zeros((b, 1, 1, t), jnp.float32)
            for causal in (False, True):
                for use_bias in (False, True):
                    bi = bias if use_bias else None

                    def g(fn):
                        return jax.jit(jax.grad(
                            lambda q, k, v: jnp.sum(
                                fn(q, k, v).astype(jnp.float32)),
                            argnums=(0, 1, 2)))

                    fl = g(lambda q, k, v: fa.flash_attention(
                        q, k, v, *fa._auto_blocks(t), bias=bi,
                        causal=causal))
                    xl = g(lambda q, k, v: fa.xla_attention(
                        q, k, v, bias=bi, causal=causal))
                    try:
                        t_fl = timed(fl, bufs)
                    except Exception as e:
                        t_fl = None
                    t_xl = timed(xl, bufs)
                    rows.append({
                        "d": d, "h": h, "t": t, "b": b,
                        "causal": causal, "bias": use_bias,
                        "flash_ms": (None if t_fl is None
                                     else round(t_fl, 3)),
                        "xla_ms": round(t_xl, 3),
                        "flash_speedup": (None if t_fl is None else
                                          round(t_xl / t_fl, 3))})
                    print(json.dumps(rows[-1]), flush=True)

    # block sweep at the causal flagship shape (t=2048, d=64)
    b, h, t, d = 8, 12, 2048, 64
    mk = lambda: jnp.asarray(rng.normal(size=(b, h, t, d)), jnp.bfloat16)
    bufs = [(mk(), mk(), mk()) for _ in range(4)]
    blocks = []
    for bq in (256, 512, 1024):
        for bk in (256, 512, 1024, 2048):
            if t % bq or t % bk:
                continue
            try:
                f = jax.jit(jax.grad(
                    lambda q, k, v, _bq=bq, _bk=bk: jnp.sum(
                        fa.flash_attention(q, k, v, _bq, _bk,
                                           causal=True).astype(
                                               jnp.float32)),
                    argnums=(0, 1, 2)))
                ms = timed(f, bufs)
                blocks.append({"blk_q": bq, "blk_k": bk,
                               "ms": round(ms, 3)})
                print(json.dumps(blocks[-1]), flush=True)
            except Exception as e:
                blocks.append({"blk_q": bq, "blk_k": bk,
                               "error": str(e)[:120]})

    out = {"rows": rows, "causal_t2048_block_sweep": blocks,
           "protocol": "fwd+bwd grad-of-sum, 4 rotating buffers, "
                       "30 iters, scalar readback, warm-up discarded"}
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "FLASH_SWEEP_r04.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print("wrote", path)


if __name__ == "__main__":
    main()
