#!/usr/bin/env python
"""Static-analysis CI gate.

Runs ``deeplearning4j_tpu.analysis`` over the package, diffs the
findings against the checked-in ``ANALYSIS_BASELINE.json``, and:

* exits 0 when every finding is covered by the baseline (stale keys —
  fixed debt — are reported but do not fail);
* exits 1 on any NEW finding, printing a diff-style report
  (``+`` new finding, ``-`` stale baseline key);
* ``--update-baseline`` rewrites the baseline to match the current
  findings (preserving the justifications of surviving keys — fill in
  a justification for every new entry before committing!) and exits 0.

Wired alongside ``check_telemetry.py`` / ``chaos_smoke.py``:

    JAX_PLATFORMS=cpu python scripts/lint_gate.py
    JAX_PLATFORMS=cpu python scripts/lint_gate.py --update-baseline

The lint is pure AST walking — nothing in the linted tree is imported
or executed, so the gate is safe to run on broken work-in-progress
trees (a file that does not parse is itself a finding).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(REPO, "ANALYSIS_BASELINE.json")
DEFAULT_PATHS = [os.path.join(REPO, "deeplearning4j_tpu")]


def main(argv=None) -> int:
    from deeplearning4j_tpu.analysis.cli import emit_telemetry, lint_paths
    from deeplearning4j_tpu.analysis.findings import Baseline

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", default=None)
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--update-baseline", action="store_true")
    ap.add_argument("--telemetry", action="store_true",
                    help="count findings into the metrics registry")
    args = ap.parse_args(argv)

    paths = args.paths or DEFAULT_PATHS
    findings = lint_paths(paths, root=REPO)
    if args.telemetry:
        emit_telemetry(findings)

    if args.update_baseline:
        old = Baseline.load(args.baseline) if \
            os.path.exists(args.baseline) else Baseline()
        new_bl = old.updated_with(findings)
        new_bl.save(args.baseline)
        missing = [k for k, v in new_bl.entries.items()
                   if not v["justification"]]
        print(f"baseline updated: {len(new_bl.entries)} key(s) -> "
              f"{args.baseline}")
        if missing:
            print(f"!! {len(missing)} entr(y/ies) lack a justification "
                  "— fill them in before committing:")
            for k in missing:
                print(f"   {k}")
        return 0

    if not os.path.exists(args.baseline):
        print(f"no baseline at {args.baseline}; every finding is new "
              "(create one with --update-baseline)")
        baseline = Baseline()
    else:
        baseline = Baseline.load(args.baseline)
    new, baselined, stale = baseline.diff(findings)

    for f in new:
        print(f"+ {f.render()}")
    for k in stale:
        print(f"- [stale baseline key] {k}")
    print(f"== lint gate: {len(findings)} finding(s), "
          f"{len(baselined)} baselined, {len(new)} NEW, "
          f"{len(stale)} stale")
    if new:
        print("FAIL: new findings — fix them, or (with a written "
              "justification) add them via --update-baseline")
        return 1
    if stale:
        print("note: stale keys are fixed debt; prune with "
              "--update-baseline")
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
