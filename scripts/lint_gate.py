#!/usr/bin/env python
"""Static-analysis CI gate — whole-package, cross-module, cached.

Runs ``deeplearning4j_tpu.analysis`` over the package in whole-package
mode (per-module rules PLUS the cross-module JIT106/CONC205/CONC206
passes over the package index), diffs the findings against the
checked-in ``ANALYSIS_BASELINE.json``, and:

* exits 0 when every finding is covered by the baseline (stale keys —
  fixed debt — are reported but do not fail);
* exits 1 on any NEW finding, printing a diff-style report
  (``+`` new finding, ``-`` stale baseline key);
* ``--update-baseline`` rewrites the baseline to match the current
  findings (preserving the justifications of surviving keys — fill in
  a justification for every new entry before committing!) and exits 0;
* ``--changed-only`` gates only on new findings in files the working
  tree changed vs ``--diff-base`` (default HEAD).  The whole package
  is still indexed — a change in module A can create a finding in
  module B, and the per-file-mtime cache keeps the full run at
  sub-second warm — but the verdict is scoped to the diff, for
  fast pre-commit loops.  Off-diff new findings are reported as a
  note, not a failure;
* ``--audit-baseline`` audits the debt ledger: stale keys (fixed debt
  still listed) and entries with no justification fail the audit;
* ``--prune`` rewrites the baseline dropping stale keys (entries
  whose finding no longer fires anywhere in the package), preserving
  the justifications of surviving keys;
* ``--check`` makes stale keys a FAILURE rather than a note — the CI
  invocation, so baseline rot cannot accumulate silently.

The ``scripts/`` directory itself is indexed as an AUX seed: its
module-level entry points root the lock-order pass's
thread-reachability (CONC301/302/303), but findings are only ever
reported inside the package.

Wired alongside ``check_telemetry.py`` / ``chaos_smoke.py``:

    JAX_PLATFORMS=cpu python scripts/lint_gate.py
    JAX_PLATFORMS=cpu python scripts/lint_gate.py --changed-only
    JAX_PLATFORMS=cpu python scripts/lint_gate.py --audit-baseline
    JAX_PLATFORMS=cpu python scripts/lint_gate.py --update-baseline

The lint is pure AST walking — nothing in the linted tree is imported
or executed, so the gate is safe to run on broken work-in-progress
trees (a file that does not parse is itself a finding).
"""
import argparse
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(REPO, "ANALYSIS_BASELINE.json")
DEFAULT_PATHS = [os.path.join(REPO, "deeplearning4j_tpu")]
DEFAULT_CACHE = os.path.join(REPO, ".dl4j_lint_cache.json")
#: aux seed dirs: scripts/ entry points root the lock-order pass's
#: thread-reachability (no findings are reported in them)
DEFAULT_SEED_DIRS = [os.path.join(REPO, "scripts")]


def changed_files(diff_base: str):
    """Repo-relative paths the working tree changed vs ``diff_base``
    (tracked modifications + untracked .py files)."""
    out = set()
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", diff_base, "--"],
            cwd=REPO, capture_output=True, text=True, check=True)
        out.update(line.strip() for line in diff.stdout.splitlines()
                   if line.strip())
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=REPO, capture_output=True, text=True, check=True)
        out.update(line.strip() for line in untracked.stdout.splitlines()
                   if line.strip())
    except (OSError, subprocess.CalledProcessError) as e:
        raise SystemExit(f"--changed-only needs a git tree: {e}")
    return out


def main(argv=None) -> int:
    from deeplearning4j_tpu.analysis.cli import (_merge_stats,
                                                 emit_telemetry,
                                                 lint_package,
                                                 lint_paths)
    from deeplearning4j_tpu.analysis.findings import Baseline

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", default=None)
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--update-baseline", action="store_true")
    ap.add_argument("--audit-baseline", action="store_true",
                    help="report stale / unjustified baseline keys; "
                         "exit 1 when any exist")
    ap.add_argument("--prune", action="store_true",
                    help="rewrite the baseline dropping keys whose "
                         "finding no longer fires anywhere (fixed "
                         "debt), preserving surviving justifications")
    ap.add_argument("--check", action="store_true",
                    help="CI mode: ALSO fail (exit 1) when pruneable "
                         "stale baseline keys exist — baseline rot "
                         "is a gate failure, not a note")
    ap.add_argument("--seed-dir", action="append", default=None,
                    help="aux directory whose entry points seed the "
                         "lock-order pass (default: scripts/; pass "
                         "an empty value to disable)")
    ap.add_argument("--changed-only", action="store_true",
                    help="gate only on new findings in files changed "
                         "vs --diff-base (full package still indexed)")
    ap.add_argument("--diff-base", default="HEAD")
    ap.add_argument("--cache", default=DEFAULT_CACHE)
    ap.add_argument("--no-cache", action="store_true")
    ap.add_argument("--no-cross", action="store_true",
                    help="per-module rules only (PR 4 behavior)")
    ap.add_argument("--telemetry", action="store_true",
                    help="count findings into the metrics registry")
    args = ap.parse_args(argv)

    paths = args.paths or DEFAULT_PATHS
    seed_dirs = DEFAULT_SEED_DIRS if args.seed_dir is None \
        else [d for d in args.seed_dir if d]
    findings, stats = [], None
    for p in paths:
        if os.path.isdir(p):
            fs, st = lint_package(
                p, root=REPO,
                cache_path=None if args.no_cache else args.cache,
                cross=not args.no_cross, seed_dirs=seed_dirs)
            findings.extend(fs)
            stats = _merge_stats(stats, st)
        else:
            findings.extend(lint_paths([p], root=REPO))
    if args.telemetry:
        emit_telemetry(findings)
        if stats is not None:
            from deeplearning4j_tpu.analysis.package_index import (
                emit_index_telemetry)
            emit_index_telemetry(stats)

    if args.update_baseline:
        old = Baseline.load(args.baseline) if \
            os.path.exists(args.baseline) else Baseline()
        new_bl = old.updated_with(findings)
        new_bl.save(args.baseline)
        missing = [k for k, v in new_bl.entries.items()
                   if not v["justification"]]
        print(f"baseline updated: {len(new_bl.entries)} key(s) -> "
              f"{args.baseline}")
        if missing:
            print(f"!! {len(missing)} entr(y/ies) lack a justification "
                  "— fill them in before committing:")
            for k in missing:
                print(f"   {k}")
        return 0

    if not os.path.exists(args.baseline):
        print(f"no baseline at {args.baseline}; every finding is new "
              "(create one with --update-baseline)")
        baseline = Baseline()
    else:
        baseline = Baseline.load(args.baseline)
    new, baselined, stale = baseline.diff(findings)

    if args.prune:
        if not stale:
            print("baseline already tight: nothing to prune "
                  f"({len(baseline.entries)} key(s))")
            return 0
        for k in stale:
            del baseline.entries[k]
        baseline.save(args.baseline)
        for k in stale:
            print(f"- [pruned] {k}")
        print(f"pruned {len(stale)} stale key(s); "
              f"{len(baseline.entries)} remain -> {args.baseline}")
        return 0

    if args.audit_baseline:
        unjustified = sorted(k for k, v in baseline.entries.items()
                             if not v["justification"])
        for k in stale:
            print(f"- [stale: no longer produced] {k}")
        for k in unjustified:
            print(f"? [no justification] {k}")
        print(f"== baseline audit: {len(baseline.entries)} key(s), "
              f"{len(stale)} stale, {len(unjustified)} unjustified")
        if stale or unjustified:
            print("FAIL: prune stale keys with --update-baseline and "
                  "justify every accepted finding")
            return 1
        print("OK")
        return 0

    scope_note = ""
    if args.changed_only:
        changed = changed_files(args.diff_base)
        off_diff = [f for f in new if f.path not in changed]
        new = [f for f in new if f.path in changed]
        if off_diff:
            scope_note = (f"note: {len(off_diff)} new finding(s) "
                          "OUTSIDE the diff (run the full gate): " +
                          ", ".join(sorted({f.path for f in off_diff})))

    for f in new:
        print(f"+ {f.render()}")
    for k in stale:
        print(f"- [stale baseline key] {k}")
    idx = (f", {stats.modules} modules indexed "
           f"({stats.cache_hits} cached)" if stats else "")
    print(f"== lint gate: {len(findings)} finding(s), "
          f"{len(baselined)} baselined, {len(new)} NEW, "
          f"{len(stale)} stale{idx}")
    if scope_note:
        print(scope_note)
    if new:
        print("FAIL: new findings — fix them, or (with a written "
              "justification) add them via --update-baseline")
        return 1
    if stale:
        if args.check:
            print("FAIL: stale baseline keys (fixed debt still "
                  "listed) — prune them with --prune")
            return 1
        print("note: stale keys are fixed debt; prune with --prune")
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
