#!/usr/bin/env python
"""Diagnose the FLASH_SWEEP_r04 plain-variant anomaly (VERDICT r5 ask 2):
flash with causal=False, bias=None timed ~50 ms FLAT across shapes whose
total input bytes are constant but whose FLOPs vary 8x — honest kernel
time tracks FLOPs, so something per-call and size-proportional is wrong.
Hypotheses: (a) per-call recompilation, (b) degenerate Mosaic schedule,
(c) host transfer / sync forced only on the no-mask path.

Probes, at d=128 t=2048 b=8 h=6 (flagship-adjacent):
  1. log_compiles on — count compiles across the timed loop per variant
  2. fwd-only vs fwd+bwd per variant
  3. plain fwd with jax.profiler trace → count device kernels
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

fa = None


def timed(fn, bufs, iters=20, tag=""):
    out = fn(*bufs[0])
    jax.block_until_ready(out)
    for a in bufs:
        out = fn(*a)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for i in range(iters):
        out = fn(*bufs[i % len(bufs)])
    leaf = jax.tree_util.tree_leaves(out)[0]
    _ = float(jnp.sum(leaf.astype(jnp.float32)))
    ms = (time.perf_counter() - t0) / iters * 1e3
    print(f"  {tag}: {ms:.2f} ms/iter", flush=True)
    return ms


def main():
    global fa
    import deeplearning4j_tpu.kernels  # noqa: F401
    fa = sys.modules["deeplearning4j_tpu.kernels.flash_attention"]
    assert jax.default_backend() == "tpu"
    rng = np.random.default_rng(0)
    b, h, t, d = 8, 6, 2048, 128
    mk = lambda: jnp.asarray(rng.normal(size=(b, h, t, d)), jnp.bfloat16)
    bufs = [(mk(), mk(), mk()) for _ in range(4)]
    bias = jnp.zeros((b, 1, 1, t), jnp.float32)
    blocks = fa._auto_blocks(t)
    print("blocks:", blocks)

    # throwaway first loop (poisoned through the tunnel)
    f_warm = jax.jit(lambda q, k, v: fa.xla_attention(q, k, v))
    timed(f_warm, bufs, tag="warmup-xla (discard)")

    variants = {
        "plain fwd": jax.jit(lambda q, k, v: fa.flash_attention(
            q, k, v, *blocks)),
        "bias fwd": jax.jit(lambda q, k, v: fa.flash_attention(
            q, k, v, *blocks, bias=bias)),
        "causal fwd": jax.jit(lambda q, k, v: fa.flash_attention(
            q, k, v, *blocks, causal=True)),
    }
    for tag, fn in variants.items():
        timed(fn, bufs, tag=tag)

    def g(fn):
        return jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32)),
            argnums=(0, 1, 2)))

    gvariants = {
        "plain fwd+bwd": g(lambda q, k, v: fa.flash_attention(
            q, k, v, *blocks)),
        "bias fwd+bwd": g(lambda q, k, v: fa.flash_attention(
            q, k, v, *blocks, bias=bias)),
    }
    for tag, fn in gvariants.items():
        timed(fn, bufs, tag=tag)

    # compile-count probe: re-time plain fwd with log_compiles
    print("\n-- log_compiles probe (plain fwd, 6 calls) --", flush=True)
    import logging
    logging.basicConfig(level=logging.WARNING)
    with jax.log_compiles(True):
        fn = variants["plain fwd"]
        for i in range(6):
            t0 = time.perf_counter()
            out = fn(*bufs[i % 4])
            jax.block_until_ready(out)
            print(f"  call {i}: {(time.perf_counter()-t0)*1e3:.2f} ms",
                  flush=True)


if __name__ == "__main__":
    main()
