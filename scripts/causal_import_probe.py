#!/usr/bin/env python
"""Imported-causal-graph fine-tune ON SILICON (VERDICT r4 item 6's
'done' bar): import the toy frozen GPT (t=512, additive tril mask),
fuse to causal fused_attention, fine-tune with the flash kernel's
CAUSAL path route-probe-verified, record CAUSAL_IMPORT_r05.json."""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    assert jax.default_backend() == "tpu", "probe needs the real chip"
    from deeplearning4j_tpu import kernels
    from deeplearning4j_tpu.autodiff import TrainingConfig
    from deeplearning4j_tpu.autodiff.rewrites import optimize_for_tpu
    from deeplearning4j_tpu.autodiff.tf_import import import_frozen_pb
    from deeplearning4j_tpu.optimize.updaters import Adam

    pb = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests", "fixtures",
        "gpt_toy_frozen.pb")
    sd = import_frozen_pb(pb)
    stats = optimize_for_tpu(sd, compute_dtype="bfloat16")
    fused = [n for n in sd.ops if n.op_name == "fused_attention"]
    causal_sites = sum(1 for n in fused if n.attrs.get("causal"))

    pooled = sd.reduce_mean(sd.vars["Identity"], axis=1)
    w = sd.var("cls_W", np.random.default_rng(0).normal(
        scale=0.02, size=(64, 2)).astype(np.float32))
    logits = sd.matmul(pooled, w, name="logits")
    labels = sd.placeholder("labels", (None,), "int32")
    per_ex = sd.op("sparse_softmax_cross_entropy_with_logits", labels,
                   logits)
    sd.set_loss_variables(sd.reduce_mean(per_ex, name="loss"))
    sd.set_training_config(TrainingConfig(
        updater=Adam(learning_rate=2e-5),
        data_set_feature_mapping=["i"],
        data_set_label_mapping=["labels"],
        compute_dtype="bfloat16"))

    batch, t = 32, 512
    rng = np.random.default_rng(0)
    step_fn, updater = sd._train_step_fn(["i", "labels"])
    params = {k: jnp.asarray(v) for k, v in sd._param_values().items()}
    opt_state = updater.init_state(params)
    bufs = []
    for _ in range(4):
        ids = rng.integers(0, 500, (batch, t)).astype(np.int32)
        # a learnable lexical rule: class = whether token 7 appears
        labs = (np.any(ids == 7, axis=1)).astype(np.int32)
        bufs.append({"i": jnp.asarray(ids), "labels": jnp.asarray(labs)})

    kernels.reset_route_log()
    params, opt_state, loss = step_fn(
        params, opt_state, jnp.asarray(0, jnp.int32), bufs[0])
    loss_first = float(loss)
    routes = kernels.route_log()
    flash_routes = sum(1 for r in routes if r[0] == "flash")
    n_steps = 60
    t0 = time.perf_counter()
    for i in range(n_steps):
        params, opt_state, loss = step_fn(
            params, opt_state, jnp.asarray(i + 1, jnp.int32),
            bufs[i % 4])
    loss_last = float(loss)
    dt = time.perf_counter() - t0
    out = {
        "metric": "imported_causal_gpt_finetune",
        "fused_attention_sites": stats["attention"],
        "causal_sites": causal_sites,
        "flash_routes_traced": flash_routes,
        "routes": [list(r) for r in routes[:8]],
        "batch": batch, "seq_len": t,
        "ms_per_step": round(dt / n_steps * 1e3, 3),
        "loss_first": round(loss_first, 4),
        "loss_last": round(loss_last, 4),
    }
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "CAUSAL_IMPORT_r05.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
