#!/usr/bin/env python
"""Tier-1 log comparator — name the failures a saturated run hides.

The tier-1 gate runs ``pytest -q`` under a hard wall-clock budget and
is EXPECTED to be cut off by ``timeout`` (rc 124): the signal is the
glyph stream, not the exit code, and the short-summary section that
would name failures usually never prints.  Comparing two runs by
counting dots alone can mask a regression that trades one failure for
another, so this script maps each progress glyph back to a TEST NAME
by position against the collection order (stable: the gate pins
``-p no:randomly``), then diffs the two runs name-by-name:

    python scripts/t1_compare.py BASELINE.log CURRENT.log
    python scripts/t1_compare.py BASELINE.log CURRENT.log \
        --collect collected.txt      # reuse a saved collection list

Without ``--collect`` the collection order is recomputed by running
``pytest --collect-only -q`` with the gate's own flags (slow — the
repo imports heavy modules at collection).  Output: the DOTS_PASSED
delta, failures that vanished, and NOVEL failure names; exit 1 iff
the current run shows an F/E at a position the baseline passed (or
any F/E past the baseline's truncation point on a test the baseline
never reached is reported but NOT novel — it was unobserved, not
green).
"""
from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys

#: a pytest -q progress line: glyphs, optionally a percent marker
GLYPH_RE = re.compile(r"^([.FEsxX]+)( *\[ *\d+%\])?$")

#: the gate's own collection flags (ROADMAP tier-1 recipe)
COLLECT_ARGS = ["-m", "pytest", "tests/", "-q", "-m", "not slow",
                "--collect-only", "--continue-on-collection-errors",
                "-p", "no:cacheprovider", "-p", "no:xdist",
                "-p", "no:randomly"]


def parse_glyphs(path: str) -> str:
    """Concatenate the progress glyphs of one ``pytest -q`` log, in
    order.  A timeout-truncated log just yields a shorter stream."""
    out = []
    with open(path, errors="replace") as f:
        for line in f:
            m = GLYPH_RE.match(line.rstrip("\n"))
            if m:
                out.append(m.group(1))
    return "".join(out)


def collection_order(collect_file=None):
    """Test ids in collection order: from a saved ``--collect-only
    -q`` listing, or by running collection with the gate's flags."""
    if collect_file:
        with open(collect_file, errors="replace") as f:
            lines = f.read().splitlines()
    else:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run([sys.executable] + COLLECT_ARGS,
                              capture_output=True, text=True, env=env)
        lines = proc.stdout.splitlines()
    return [ln.strip() for ln in lines
            if "::" in ln and " " not in ln.strip()]


def outcomes(glyphs: str, order):
    """Position-map glyphs to names.  Returns (by_name, n_unmapped):
    glyph i belongs to test i while the collection list covers it;
    glyphs past the list (collection drift) stay unmapped and are
    surfaced rather than silently dropped."""
    by_name = {}
    for i, g in enumerate(glyphs):
        if i < len(order):
            by_name[order[i]] = g
    return by_name, max(0, len(glyphs) - len(order))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("baseline", help="baseline tier-1 log")
    ap.add_argument("current", help="current tier-1 log")
    ap.add_argument("--collect", default=None,
                    help="saved `pytest --collect-only -q` output "
                    "(skips recomputing collection)")
    args = ap.parse_args(argv)

    base_g = parse_glyphs(args.baseline)
    cur_g = parse_glyphs(args.current)
    order = collection_order(args.collect)
    if not order:
        print(json.dumps({"ok": False,
                          "error": "empty collection order"}))
        return 2
    base, base_extra = outcomes(base_g, order)
    cur, cur_extra = outcomes(cur_g, order)

    def bad(d):
        return {n for n, g in d.items() if g in "FE"}

    novel = sorted(n for n in bad(cur)
                   if base.get(n) not in (None, "F", "E"))
    unobserved = sorted(n for n in bad(cur) if n not in base)
    fixed = sorted(n for n in bad(base)
                   if cur.get(n) not in (None, "F", "E"))
    doc = {
        "dots_baseline": base_g.count("."),
        "dots_current": cur_g.count("."),
        "dots_delta": cur_g.count(".") - base_g.count("."),
        "glyphs_baseline": len(base_g),
        "glyphs_current": len(cur_g),
        "novel_failures": novel,
        "failures_past_baseline_truncation": unobserved,
        "fixed_failures": fixed,
        "unmapped_glyphs": {"baseline": base_extra,
                            "current": cur_extra},
        "ok": not novel,
    }
    print(json.dumps(doc, indent=2))
    return 1 if novel else 0


if __name__ == "__main__":
    sys.exit(main())
