#!/usr/bin/env python
"""Run the imported-BERT fine-tune benchmark (BASELINE config 4) on the
real chip and record the artifact as FINETUNE_r05.json — >=40% MFU with
flash verifiably in the hot path AND (r5) a held-out accuracy
trajectory on the real hand-written sentiment corpus (VERDICT r4 item
3: quality evidence, not random-token memorization)."""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import bench  # noqa: E402


def main():
    r = bench.bench_bert_imported()
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "FINETUNE_r05.json")
    with open(out, "w") as f:
        json.dump(r, f, indent=1)
    print(json.dumps(r))


if __name__ == "__main__":
    main()
