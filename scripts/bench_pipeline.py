#!/usr/bin/env python
"""Real-data input-pipeline proof (VERDICT r2 item 5 / SURVEY hard
part (c)): write an ImageNet-shaped on-disk JPEG tree, measure the
host pipeline (ImageRecordReader -> RecordReaderDataSetIterator)
throughput in isolation, then run the full path
ImageRecordReader -> AsyncDataSetIterator -> ComputationGraph.fit on
the attached chip, and record everything in PIPELINE_r03.json.

Run from the repo root:  python scripts/bench_pipeline.py
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

TREE = "/tmp/dl4j_tpu_imagenet_tree"
N_IMAGES = 1024
N_CLASSES = 8
SRC_SIZE = 256          # on-disk JPEG size (ImageNet-ish)
NET_SIZE = 224


def make_tree():
    import cv2
    if os.path.exists(os.path.join(TREE, "DONE")):
        return
    rng = np.random.default_rng(0)
    for c in range(N_CLASSES):
        d = os.path.join(TREE, f"class{c:02d}")
        os.makedirs(d, exist_ok=True)
        for i in range(N_IMAGES // N_CLASSES):
            img = rng.integers(0, 255, (SRC_SIZE, SRC_SIZE, 3),
                               dtype=np.uint8)
            cv2.imwrite(os.path.join(d, f"im{i:04d}.jpg"), img)
    open(os.path.join(TREE, "DONE"), "w").write("ok")


def bench_pipeline_only():
    """Host decode->resize->batch throughput, no device involved."""
    from deeplearning4j_tpu.datavec.image import ImageRecordReader
    from deeplearning4j_tpu.datavec.iterator import (
        RecordReaderDataSetIterator)
    rr = ImageRecordReader(NET_SIZE, NET_SIZE, 3, root=TREE,
                           shuffle_seed=1)
    it = RecordReaderDataSetIterator(rr, 128, n_classes=N_CLASSES)
    n = 0
    t0 = time.perf_counter()
    for ds in it:
        n += len(np.asarray(ds.features))
    dt = time.perf_counter() - t0
    return n / dt


def bench_end_to_end():
    """Full path on the chip: reader -> async prefetch -> DP graph fit."""
    import jax
    from deeplearning4j_tpu.data.iterator import AsyncDataSetIterator
    from deeplearning4j_tpu.datavec.image import ImageRecordReader
    from deeplearning4j_tpu.datavec.iterator import (
        RecordReaderDataSetIterator)
    from deeplearning4j_tpu.zoo.resnet import ResNet50

    model = ResNet50(n_classes=N_CLASSES,
                     input_shape=(NET_SIZE, NET_SIZE, 3)).init_graph()
    # n_workers>0 uses the process-pool decode path (the production
    # configuration — thread prefetch alone loses ~4x to GIL contention
    # with the dispatch thread, measured round 3).  On THIS 1-core VM
    # extra processes only add IPC timesharing (measured 73 vs 92
    # img/s), so stay single-process here; a real v5e host sets
    # n_workers ~= cores_needed_to_feed_chip.
    workers = 2 if (os.cpu_count() or 1) > 1 else 0
    rr = ImageRecordReader(NET_SIZE, NET_SIZE, 3, root=TREE,
                           shuffle_seed=2, n_workers=workers)
    base = RecordReaderDataSetIterator(rr, 128, n_classes=N_CLASSES)
    it = AsyncDataSetIterator(base, queue_size=4)
    model.fit(it, n_epochs=1)          # warm-up epoch: XLA compile
    t0 = time.perf_counter()
    loss = model.fit(it, n_epochs=1)   # steady state
    dt = time.perf_counter() - t0
    assert np.isfinite(loss), loss
    return N_IMAGES / dt, float(loss)


def main():
    import jax
    make_tree()
    pipe_ips = bench_pipeline_only()
    e2e_ips, loss = bench_end_to_end()
    chip_ips = 2426.0       # ROOFLINE.md measured ResNet-50 rate
    host_cores = os.cpu_count()
    art = {
        "metric": "image_input_pipeline",
        "round": 3,
        "tree": {"images": N_IMAGES, "classes": N_CLASSES,
                 "jpeg_size": SRC_SIZE, "net_size": NET_SIZE},
        "host_pipeline_img_per_sec": round(pipe_ips, 1),
        "host_cores": host_cores,
        "end_to_end_fit_img_per_sec": round(e2e_ips, 1),
        "end_to_end_final_loss": round(loss, 4),
        "chip_train_img_per_sec": chip_ips,
        # pipe_ips comes from the SERIAL reader => it IS a per-core rate
        "cores_needed_to_feed_chip": round(chip_ips / pipe_ips, 1),
        "note": ("decode->resize->batch rate measured on this VM's "
                 f"{host_cores} core(s); a production host feeds the "
                 "chip by scaling the same pipeline across cores "
                 "(ImageRecordReader(n_workers=N) process-pool decode; "
                 "per-image work is embarrassingly parallel)"),
        "end_to_end_note": ("on this 1-core VM the fit-time rate is "
                            "GIL/core-contention bound (decode, batch "
                            "assembly, and device dispatch share one "
                            "core); SURVEY hard part (c) is satisfied "
                            "by the per-core decode rate x available "
                            "cores on a real TPU host (>=100)"),
    }
    with open("PIPELINE_r03.json", "w") as f:
        json.dump(art, f, indent=1)
    print(json.dumps(art, indent=1))


if __name__ == "__main__":
    main()
