#!/usr/bin/env python
"""Postmortem renderer — one merged timeline per crash bundle.

A postmortem bundle (``telemetry.flightrec``) freezes a host's last-N
flight-recorder events, the tracer's still-open spans, a final metric
snapshot and the SLO/alert state.  The fleet's trace store holds the
OTHER half of the story: the victim's requests' closed spans, beaconed
before the crash and stitched across hosts.  This script merges both
into ONE wall-clock timeline:

    python scripts/postmortem.py <shared_dir>                 # latest
    python scripts/postmortem.py <shared_dir> --bundle NAME
    python scripts/postmortem.py <shared_dir> --salvage       # promote
        # black-box ring snapshots of SIGKILL'd hosts into bundles
    python scripts/postmortem.py <shared_dir> --json          # machine

The text rendering is ordered by wall clock with one source tag per
line (``event`` = flight-recorder ring, ``span`` = stitched trace
store, ``open`` = spans still open at the crash, ``alert`` = SLO
state), so "what was this replica doing when it died" reads top to
bottom.  Importable: ``merge_timeline(bundle, trace_store)`` /
``render_timeline(entries)`` are what ``tests/test_slo.py`` and the
chaos smoke assert against.

Bundles also carry pre-crash metric HISTORY (ISSUE 16: the last
minutes of the process time-series store, downsampled).  The text
rendering appends one value timeline per series below the event
timeline — same wall-clock format, so a metric's trajectory lines up
against the events by eye — and ``--series <substr>`` (repeatable)
inlines matching series' points INTO the merged timeline as
``metric`` entries, interleaved with the decisions that moved them.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from deeplearning4j_tpu.telemetry import flightrec  # noqa: E402


def _fmt_fields(d: dict, skip=("seq", "wall", "ts", "kind")) -> str:
    return " ".join(f"{k}={v}" for k, v in d.items()
                    if k not in skip and v is not None)


def _fmt_value(v) -> str:
    """One history sample, compact: histograms dump as count/sum,
    window tuples as their elements, scalars as %g."""
    if isinstance(v, dict):
        return (f"count={v.get('count', 0):g}"
                f" sum={v.get('sum', 0.0):.6g}")
    if isinstance(v, (list, tuple)):
        return "(" + ",".join(f"{x:g}" if isinstance(x, (int, float))
                              else str(x) for x in v) + ")"
    if isinstance(v, (int, float)):
        return f"{v:.6g}"
    return str(v)


def _clock(wall: float) -> str:
    return (time.strftime("%H:%M:%S", time.localtime(wall))
            + f"{wall % 1:.3f}"[1:])


def _flatten_tree(node, out, depth=0):
    out.append({"wall": float(node.get("wall", 0.0)), "src": "span",
                "what": node["name"], "host": node.get("host"),
                "depth": depth,
                "detail": _fmt_fields(
                    dict(node.get("args", {}),
                         dur_ms=round(node.get("dur", 0.0) / 1e3, 3)))})
    for child in node.get("children", ()):
        _flatten_tree(child, out, depth + 1)


def merge_timeline(bundle: dict, trace_store=None,
                   history_series=()) -> list:
    """Merge one bundle with the trace store's stitched trees into a
    wall-clock-sorted entry list.  Only traces the bundle's OWN
    events reference are pulled from the store — a fleet aggregator
    holds every request; the postmortem wants the victim's.
    ``history_series`` substrings select bundle-history series whose
    samples interleave as ``metric`` entries."""
    entries = []
    for ev in bundle.get("events", ()):
        entries.append({"wall": float(ev.get("wall", 0.0)),
                        "src": "event", "what": ev.get("kind", "?"),
                        "host": bundle.get("host"), "depth": 0,
                        "detail": _fmt_fields(ev)})
    t_crash = float(bundle.get("t", 0.0))
    for sp in bundle.get("open_spans", ()):
        entries.append({"wall": t_crash, "src": "open",
                        "what": sp.get("name", "?"),
                        "host": bundle.get("host"), "depth": 0,
                        "detail": _fmt_fields(
                            dict(sp.get("args", {}),
                                 still_open_at_crash=True))})
    slo = bundle.get("slo") or {}
    for alert in slo.get("alerts", ()):
        if alert.get("state") == "inactive":
            continue
        entries.append({
            "wall": float(alert.get("t_fired") or t_crash),
            "src": "alert", "what": f"slo:{alert['slo']}",
            "host": bundle.get("host"), "depth": 0,
            "detail": (f"state={alert['state']} "
                       f"budget_remaining="
                       f"{alert['budget_remaining']:.3g} "
                       f"burns={alert['burns']}")})
    if history_series:
        series = (bundle.get("history") or {}).get("series") or {}
        for key in sorted(series):
            if not any(pat in key for pat in history_series):
                continue
            for point in series[key].get("points", ()):
                entries.append({"wall": float(point[0]),
                                "src": "metric", "what": key,
                                "host": bundle.get("host"),
                                "depth": 0,
                                "detail": _fmt_value(point[1])})
    if trace_store is not None:
        traces = sorted({ev.get("trace")
                         for ev in bundle.get("events", ())
                         if ev.get("trace")})
        for tid in traces:
            tree = trace_store.tree(tid)
            if tree.get("root"):
                _flatten_tree(tree["root"], entries)
            for orphan in tree.get("orphans", ()):
                _flatten_tree(orphan, entries)
    entries.sort(key=lambda e: (e["wall"], e["src"], e["what"]))
    return entries


def render_timeline(entries, reason: str = "") -> str:
    lines = [f"postmortem timeline ({len(entries)} entries)"
             + (f" — {reason}" if reason else "")]
    for e in entries:
        pad = "  " * e.get("depth", 0)
        lines.append(f"{_clock(e['wall'])} [{e['src']:>6}] "
                     f"{pad}{e['what']}"
                     + (f" ({e['host']})" if e.get("host") else "")
                     + (f" {e['detail']}" if e.get("detail") else ""))
    return "\n".join(lines)


def render_history(bundle: dict, width: int = 8) -> str:
    """One value timeline per history series: the span's wall-clock
    bounds (same format as the event timeline — line them up by eye)
    and up to ``width`` evenly-strided samples showing the
    trajectory into the crash.  Empty string when the bundle
    predates bundle history."""
    history = bundle.get("history") or {}
    series = history.get("series") or {}
    if not series:
        return ""
    lines = [f"pre-crash metric history ({len(series)} series, "
             f"last {history.get('window_s', 0.0):g}s)"]
    for key in sorted(series):
        pts = series[key].get("points") or []
        if not pts:
            continue
        stride = max(1, -(-len(pts) // max(1, int(width))))
        shown = list(pts[::stride])
        if shown[-1] is not pts[-1]:
            shown.append(pts[-1])
        vals = " | ".join(_fmt_value(p[1]) for p in shown)
        lines.append(f"  {_clock(float(pts[0][0]))}"
                     f"..{_clock(float(pts[-1][0]))} "
                     f"{key} [{len(pts)}pt]: {vals}")
    return "\n".join(lines)


def build_trace_store(shared_dir: str):
    """The aggregator's view of the shared dir's beacons (None when
    no beacon directory exists — the bundle still renders alone)."""
    from deeplearning4j_tpu import telemetry
    from deeplearning4j_tpu.telemetry.fleet import BEACON_DIRNAME
    if not os.path.isdir(os.path.join(shared_dir, BEACON_DIRNAME)):
        return None
    fr = telemetry.FleetRegistry(shared_dir, stale_after_s=float("inf"))
    fr.refresh()
    return fr.traces


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("shared_dir", help="the fleet's shared directory "
                    "(beacons + _postmortem bundles)")
    ap.add_argument("--bundle", default="latest",
                    help="bundle file name (or 'latest')")
    ap.add_argument("--salvage", action="store_true",
                    help="promote SIGKILL'd hosts' black-box ring "
                    "snapshots into bundles first")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the merged timeline as JSON")
    ap.add_argument("--series", action="append", default=[],
                    help="substring of bundle-history series to "
                    "inline into the timeline (repeatable)")
    args = ap.parse_args(argv)

    if args.salvage:
        for path in flightrec.salvage_bundles(args.shared_dir):
            print(f"salvaged: {path}", file=sys.stderr)
    bundles = flightrec.list_bundles(args.shared_dir)
    if not bundles:
        print(json.dumps({"ok": False,
                          "error": "no postmortem bundles found"}))
        return 1
    if args.bundle == "latest":
        path = bundles[-1]
    else:
        match = [p for p in bundles
                 if os.path.basename(p) == args.bundle]
        if not match:
            print(json.dumps({
                "ok": False,
                "error": f"bundle {args.bundle!r} not found",
                "bundles": [os.path.basename(p) for p in bundles]}))
            return 1
        path = match[0]
    bundle = flightrec.load_bundle(path)
    entries = merge_timeline(bundle, build_trace_store(args.shared_dir),
                             history_series=args.series)
    if args.as_json:
        print(json.dumps({"ok": True, "bundle": os.path.basename(path),
                          "reason": bundle.get("reason"),
                          "host": bundle.get("host"),
                          "entries": entries,
                          "history": bundle.get("history")}))
    else:
        print(render_timeline(entries, bundle.get("reason", "")))
        history = render_history(bundle)
        if history:
            print("\n" + history)
        print(f"\nbundle: {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
