#!/usr/bin/env python
"""ParallelInference dynamic-batching benchmark (VERDICT r3 item 8):
p50/p99 request latency + sustained throughput vs offered concurrency
on the real chip, written to SERVING_r05.json.

Model: zoo SimpleCNN at 48x48x3 (a realistic serving-sized CNN).  Each
client thread issues single-example blocking ``output(x)`` requests in
a closed loop; the server coalesces concurrent requests into one
bucketed forward (the DL4J BATCHED inference mode).  Latency is
per-request wall time; a 2 s warmup per concurrency level is discarded.
"""
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def run_level(pi, n_clients: int, seconds: float = 6.0,
              warmup: float = 2.0):
    rng = np.random.default_rng(0)
    xs = [rng.normal(size=(1, 48, 48, 3)).astype(np.float32)
          for _ in range(8)]
    stop = time.perf_counter() + warmup + seconds
    t_measure = time.perf_counter() + warmup
    lat, count = [], [0]
    lock = threading.Lock()

    def client(cid):
        i = 0
        while True:
            now = time.perf_counter()
            if now >= stop:
                return
            t0 = time.perf_counter()
            pi.output(xs[(cid + i) % len(xs)])
            t1 = time.perf_counter()
            i += 1
            if t0 >= t_measure and t1 < stop:
                # count only requests fully inside the window — else
                # up to n_clients stragglers overstate req/s
                with lock:
                    lat.append(t1 - t0)
                    count[0] += 1

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    lat = np.asarray(sorted(lat))
    return {
        "concurrency": n_clients,
        "requests": int(count[0]),
        "throughput_req_s": round(count[0] / seconds, 1),
        "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 2),
        "p90_ms": round(float(np.percentile(lat, 90)) * 1e3, 2),
        "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 2),
    }


def model_time_ms(model, batch: int):
    """Pure per-forward DEVICE time at this batch size, via the
    differential two-scan-length protocol (the per-call wall numbers
    below are tunnel-RTT-dominated ~110 ms; this is the number that
    transfers to a direct-attached deployment)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    rng = np.random.default_rng(1)
    xs = jnp.asarray(rng.normal(size=(4, batch, 48, 48, 3)), jnp.float32)
    params, state = model.params_tree, model.state_tree

    def fwd(x):
        return jnp.sum(model._forward_infer(params, state, x)
                       .astype(jnp.float32))

    def make_run(n):
        @jax.jit
        def run(xs, seed):
            xs = xs + seed
            def body(c, i):
                return c + fwd(xs[i % 4]), None
            c, _ = lax.scan(body, 0.0, jnp.arange(n))
            return c
        return run

    r1, r2 = make_run(8), make_run(72)
    _ = float(r1(xs, 1e-6)); _ = float(r2(xs, 2e-6))
    def wall(r, seed):
        t0 = time.perf_counter()
        _ = float(r(xs, seed))
        return time.perf_counter() - t0
    t1 = min(wall(r1, 3e-6), wall(r1, 4e-6), wall(r1, 5e-6))
    t2 = min(wall(r2, 6e-6), wall(r2, 7e-6), wall(r2, 8e-6))
    return (t2 - t1) / 64 * 1e3


def main():
    import jax
    from deeplearning4j_tpu.parallel.inference import ParallelInference
    from deeplearning4j_tpu.zoo.simple_cnn import SimpleCNN

    backend = jax.default_backend()
    model = SimpleCNN(n_classes=10, input_shape=(48, 48, 3)).init_graph()
    rows = []
    with ParallelInference(model, batch_limit=64, queue_limit=256,
                           timeout_ms=2.0) as pi:
        pi.output(np.zeros((1, 48, 48, 3), np.float32))  # compile
        for n in (1, 4, 16, 64):
            rows.append(run_level(pi, n))
            print(json.dumps(rows[-1]), flush=True)
    mt = {str(b): round(model_time_ms(model, b), 3)
          for b in (1, 16, 64)}
    out = {"backend": backend, "model": "SimpleCNN 48x48x3",
           "batch_limit": 64, "mode": "BATCHED (dynamic coalescing, "
           "power-of-two padding buckets)", "levels": rows,
           "device_model_time_ms_per_forward": mt,
           "model_time_note": "pure device time per batched forward "
           "(differential two-scan-length protocol; tunnel RTT "
           "cancels) — the wall p50 above is ~110 ms axon round-trip "
           "dominated and does NOT transfer to direct-attached "
           "deployments; these numbers do"}
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "SERVING_r05.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print("wrote", path)


if __name__ == "__main__":
    main()
