#!/usr/bin/env python
"""ParallelInference dynamic-batching benchmark (VERDICT r3 item 8):
p50/p99 request latency + sustained throughput vs offered concurrency
on the real chip, written to SERVING_r04.json.

Model: zoo SimpleCNN at 48x48x3 (a realistic serving-sized CNN).  Each
client thread issues single-example blocking ``output(x)`` requests in
a closed loop; the server coalesces concurrent requests into one
bucketed forward (the DL4J BATCHED inference mode).  Latency is
per-request wall time; a 2 s warmup per concurrency level is discarded.
"""
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def run_level(pi, n_clients: int, seconds: float = 6.0,
              warmup: float = 2.0):
    rng = np.random.default_rng(0)
    xs = [rng.normal(size=(1, 48, 48, 3)).astype(np.float32)
          for _ in range(8)]
    stop = time.perf_counter() + warmup + seconds
    t_measure = time.perf_counter() + warmup
    lat, count = [], [0]
    lock = threading.Lock()

    def client(cid):
        i = 0
        while True:
            now = time.perf_counter()
            if now >= stop:
                return
            t0 = time.perf_counter()
            pi.output(xs[(cid + i) % len(xs)])
            t1 = time.perf_counter()
            i += 1
            if t0 >= t_measure and t1 < stop:
                # count only requests fully inside the window — else
                # up to n_clients stragglers overstate req/s
                with lock:
                    lat.append(t1 - t0)
                    count[0] += 1

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    lat = np.asarray(sorted(lat))
    return {
        "concurrency": n_clients,
        "requests": int(count[0]),
        "throughput_req_s": round(count[0] / seconds, 1),
        "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 2),
        "p90_ms": round(float(np.percentile(lat, 90)) * 1e3, 2),
        "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 2),
    }


def main():
    import jax
    from deeplearning4j_tpu.parallel.inference import ParallelInference
    from deeplearning4j_tpu.zoo.simple_cnn import SimpleCNN

    backend = jax.default_backend()
    model = SimpleCNN(n_classes=10, input_shape=(48, 48, 3)).init_graph()
    rows = []
    with ParallelInference(model, batch_limit=64, queue_limit=256,
                           timeout_ms=2.0) as pi:
        pi.output(np.zeros((1, 48, 48, 3), np.float32))  # compile
        for n in (1, 4, 16, 64):
            rows.append(run_level(pi, n))
            print(json.dumps(rows[-1]), flush=True)
    out = {"backend": backend, "model": "SimpleCNN 48x48x3",
           "batch_limit": 64, "mode": "BATCHED (dynamic coalescing, "
           "power-of-two padding buckets)", "levels": rows}
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "SERVING_r04.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print("wrote", path)


if __name__ == "__main__":
    main()
