#!/usr/bin/env python
"""Mesh-sharded decode benchmark -> SERVING_MESH_r17.json (ISSUE 17):
one replica spanning chips.  The same trace runs through a tp=1
(unsharded) and a tp=2 (data x tp NamedSharding mesh) replica —
new-tokens/s, TTFT p50/p99 and the speculative acceptance rate per
rung, outputs byte-compared across rungs so the bench fails rather
than report a rate that broke parity.

Acceptance bar (ISSUE 17): tp=2 new-tokens/s >= 0.7x the tp=1 rate —
the sharded tick's all-gather overhead never costs more than 30% of
the single-chip rate, even on the CPU smoke where both rungs share
the same silicon (on TPU the rung buys real HBM bandwidth and the
ladder climbs instead).

``--smoke`` runs the tiny CPU config (the artifact CI records); the
XLA host-device force below makes a 2-device slice available there.
The default geometry needs the real chips.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# a tp=2 rung needs two devices even on the CPU smoke; no-op when the
# flag is already set (or in-process under tests/conftest.py)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2").strip()


def main():
    smoke = "--smoke" in sys.argv[1:]
    if not smoke:
        import jax
        assert jax.default_backend() == "tpu", \
            "needs the real chips (or pass --smoke for the CPU config)"
    from bench import bench_serving_mesh

    result = bench_serving_mesh(smoke=smoke)
    print(json.dumps(result))
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "SERVING_MESH_r17.json")
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
    print("wrote", path)
    ran = [r for r in result["ladder"] if "skipped" not in r]
    ok = (result["vs_baseline"] >= 0.7
          and len(ran) == len(result["ladder"])
          and all(r["spec_acceptance_rate"] == 1.0 for r in ran))
    print("acceptance:", "OK" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
