#!/usr/bin/env python
"""Paged-KV shared-prefix serve benchmark -> SERVING_DECODE_r07.json:
1/4/16 streams sharing one long system prompt through the paged
``GenerationServer`` — TTFT p50/p99 per rung, the cold-prefill vs
prefix-hit TTFT ratio (a hit prefills only the uncached suffix), and
concurrent-streams-at-fixed-HBM for the stripe vs block layouts at
mixed request lengths (a short request pins ceil(len/block_size)
blocks instead of a whole [max_len] stripe, and the shared system
prompt is resident ONCE).

Acceptance bar (ISSUE 7): prefix-hit TTFT strictly below cold TTFT,
and >= 2x concurrent streams at the stripe pool's HBM footprint.

``--smoke`` runs the tiny CPU config (the artifact CI records —
JAX_PLATFORMS=cpu friendly); the default geometry needs the real chip.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    smoke = "--smoke" in sys.argv[1:]
    if not smoke:
        import jax
        assert jax.default_backend() == "tpu", \
            "needs the real chip (or pass --smoke for the CPU config)"
    from bench import bench_serving_decode

    result = bench_serving_decode(smoke=smoke)
    print(json.dumps(result))
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "SERVING_DECODE_r07.json")
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
    print("wrote", path)
    ok = (result["prefix_hit_ttft_ratio"] < 1.0
          and result["vs_baseline"] >= 2.0)
    print("acceptance:", "OK" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
