#!/usr/bin/env python
"""Continuous-batching serve benchmark on the real chip ->
SERVING_DECODE_r06.json: the ``GenerationServer`` tick-batch x
concurrency grid — aggregate new_tokens_per_sec, TTFT p50/p99, and
host syncs per token at 1/4/16 streams for each fused-scan length
K in {1,4,8,16} — vs the back-to-back single-caller ``generate()``
floor.

Two separate wins stack here.  Continuous batching (PR 2): every tick
streams the full bf16 parameter set whether 1 or 16 slots ride along
(GENERATION_r05.json measured the fixed-batch rate at 31.4% of the
params-bandwidth ideal), so multiplexing converts idle slot capacity
straight into aggregate tokens/s.  Multi-tick scan fusion (ISSUE 5):
K decode ticks run as ONE device-side ``lax.scan`` and the host polls
once per scan, so per-token dispatch overhead and the device->host
sync drop ~1/K.  Acceptance bar: K=8 at 16 streams strictly above
K=1 at 16 streams, steady-state host syncs per token <= 1/K, greedy
outputs byte-identical to offline decode (asserted by
tests/test_generation_server.py's parity matrix).
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    import jax
    assert jax.default_backend() == "tpu", "needs the real chip"
    from bench import bench_serving_decode

    result = bench_serving_decode()
    print(json.dumps(result))
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "SERVING_DECODE_r06.json")
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
    print("wrote", path)


if __name__ == "__main__":
    main()
