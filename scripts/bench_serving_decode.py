#!/usr/bin/env python
"""Continuous-batching serve benchmark on the real chip ->
SERVING_DECODE_r06.json: the ``GenerationServer`` concurrency ladder
(aggregate new_tokens_per_sec + TTFT p50/p99 at 1/4/16 streams) vs the
back-to-back single-caller ``generate()`` floor.

The decode roofline says this should be nearly free: every tick
streams the full bf16 parameter set whether 1 or 16 slots ride along
(GENERATION_r05.json measured the fixed-batch rate at 31.4% of the
params-bandwidth ideal), so continuous batching converts idle slot
capacity straight into aggregate tokens/s.  The ISSUE 2 acceptance bar
is >= 2x at 16 streams with greedy outputs byte-identical to offline
decode (asserted by tests/test_generation_server.py).
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    import jax
    assert jax.default_backend() == "tpu", "needs the real chip"
    from bench import bench_serving_decode

    result = bench_serving_decode()
    print(json.dumps(result))
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "SERVING_DECODE_r06.json")
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
    print("wrote", path)


if __name__ == "__main__":
    main()
