#!/usr/bin/env python
"""Disaggregated prefill/decode + tiered KV benchmark ->
SERVING_DISAGG_r14.json (ISSUE 14): a mixed trace of long-prompt
admissions interleaved with short-decode streams through a unified
fleet vs a role-split (prefill + decode) fleet — short-stream TTFT
p50/p99 under both — plus the tiered prefix cache's tier-hit TTFT vs
cold re-prefill at a prefix footprint larger than the device pool.

Acceptance bar (ISSUE 14): disagg short-stream TTFT p99 <= the
unified fleet's under the same trace, and tier-hit TTFT < cold
re-prefill TTFT (tier_hit_ttft_ratio < 1).  The disagg probe output
is byte-checked against the unified fleet's in-window.

``--smoke`` runs the tiny CPU config (the artifact CI records —
JAX_PLATFORMS=cpu friendly); on the shared-host CPU the role split
relieves scheduler serialization, not chip contention — the TPU
geometry is where the replicas map to real chips.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    smoke = "--smoke" in sys.argv[1:]
    if not smoke:
        import jax
        assert jax.default_backend() == "tpu", \
            "needs the real chip (or pass --smoke for the CPU config)"
    from bench import bench_serving_disagg

    result = bench_serving_disagg(smoke=smoke)
    print(json.dumps(result))
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "SERVING_DISAGG_r14.json")
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
    print("wrote", path)
    ok = (result["vs_baseline"] is not None
          and result["vs_baseline"] >= 1.0
          and result["tier"]["tier_hit_ttft_ratio"] < 1.0)
    print("acceptance:", "OK" if ok else "FAIL",
          f"(disagg p99 {result['value']}s, unified/disagg "
          f"{result['vs_baseline']}x, tier-hit ratio "
          f"{result['tier']['tier_hit_ttft_ratio']})")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
