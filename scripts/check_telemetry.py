#!/usr/bin/env python
"""Telemetry smoke check — the combined train+serve run the acceptance
bar asks for: 5 training iterations + 16 concurrent serve requests with
the Prometheus scrape endpoint live, then assert the scrape is healthy.

Fails (exit 1) when:
* fewer than 20 distinct series are exposed,
* any histogram sum is NaN,
* a required series is missing (``inference_latency_seconds`` buckets,
  ``flash_route_total{path=...}``, the ``mfu`` gauge, the fit loop's
  data-wait/step split, the ``generation_server_*`` serve-decode
  series), or
* the exported span trace or the report embedding is empty.

Runs on CPU inside the tier-1 budget (tiny MLP, seconds) — wired into
``tests/test_telemetry.py::test_check_telemetry_smoke`` un-marked (i.e.
``not slow`` selects it), and runnable standalone:

    JAX_PLATFORMS=cpu python scripts/check_telemetry.py
"""
import json
import math
import os
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

# the mesh-sharded serve smoke needs >= 2 devices; force a virtual CPU
# pair BEFORE jax initializes (no-op in-process under tests/conftest.py,
# which already forces 8)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

# Resilience-layer series that exist in EVERY process that imports the
# training/serving stack (unlabeled families expose at 0) — the plain
# smoke asserts their presence; scripts/chaos_smoke.py additionally
# asserts the labeled/event series after actually firing the faults.
RESILIENCE_SERIES = [
    "train_preemptions_total",
    "train_resumes_total",
    "bad_steps_skipped_total",
    "bad_steps_rolled_back_total",
    "train_lr_backoff_scale",
    "checkpoint_saves_total",
    "checkpoint_failures_total",
    "server_healthy",
    "serve_watchdog_restarts_total",
    "generation_server_tick_failures_total",
    "generation_server_deadline_exceeded_total",
    "generation_server_cancelled_total",
    # zero-downtime fleet layer: coordinated cross-host restart
    # (resilience/coordination.py) and surgical KV salvage
    # (generation_server pool recovery) — chaos_smoke asserts the
    # values after firing real recoveries
    "fleet_preempt_broadcasts_total",
    'fleet_resumes_total{outcome="resumed"}',
    # elastic N->M resume (ISSUE 10): the smoke below saves a world=2
    # checkpoint and fleet-resumes it at world=1, so the shrink
    # counter, world gauge and rendezvous-wait histogram carry live
    # values over the real scrape
    'fleet_elastic_resumes_total{direction="shrink"}',
    "fleet_world_size",
    "fleet_rendezvous_wait_seconds_bucket",
    "kv_slots_salvaged_total",
    "kv_slots_dropped_total",
    # paged-KV layer: block-granular salvage counters (the slot pair
    # above stays for request-level accounting)
    "kv_blocks_salvaged_total",
    "kv_blocks_dropped_total",
]

# Paged KV pool + prefix cache series (PR 7): the smoke below runs two
# same-prompt requests through a small-block server and asserts >= 1
# real prefix hit, so hits/shared carry live values on the wire.
PAGED_KV_SERIES = [
    "kv_blocks_allocated_total",
    "kv_blocks_freed_total",
    "kv_blocks_shared_total",
    "kv_pool_blocks_free",
    "prefix_cache_hits_total",
    "prefix_cache_misses_total",
    'paged_route_total{path="reference"}',
]

# Tiered-KV series (ISSUE 14): the smoke below drives two same-prefix
# requests through a tier-sized-down pool — the interleaved distinct
# prompt EVICTS the first's cached blocks (>= 1 real spill to host
# RAM), and the re-admission restores them (>= 1 tier fetch, one
# batched H2D) with the output byte-identical to the cold decode.
# The handoff pair (export_prefix -> import_blocks into a second
# server) puts real values on the kv_handoff_* counters.
TIERED_KV_SERIES = [
    # kv_pool_blocks_free itself stays in PAGED_KV_SERIES; this list
    # adds the ISSUE 14 gauge-split + tier + handoff families
    "kv_pool_blocks_evictable",
    "kv_host_tier_blocks",
    "kv_tier_spills_total",
    "kv_tier_fetches_total",
    "kv_tier_hits_total",
    "kv_tier_evictions_total",
    "kv_handoff_blocks_total",
    "kv_handoff_bytes_total",
]

# Speculative-decode series (PR 11 + ISSUE 20): the smoke below
# decodes through a draft-verified server (full-depth self-draft ->
# acceptance is exactly 1.0), so proposed/accepted and the
# acceptance-rate gauge carry live values on the wire — and the
# output is byte-compared against the non-speculative decode of the
# same prompt.  A second, SAMPLED adaptive-K server (tenant-tagged
# request) puts the adaptive-depth gauge and the per-tenant
# acceptance series on the scrape too.
SPEC_SERIES = [
    "generation_server_spec_proposed_total",
    "generation_server_spec_accepted_total",
    "generation_server_spec_acceptance_rate",
    'generation_server_scan_ticks_total{k="spec',
    "generation_server_spec_adaptive_k",
    'generation_server_tenant_spec_acceptance_rate'
    '{tenant="spec-tenant"}',
]

# Serving-fleet series (PR 9): the smoke below routes a 2-tenant
# workload through a 2-replica ServingFleet — the repeated hot-tenant
# prompt rides affinity to the warm replica (a real prefix hit there),
# so the admission/dispatch series carry live values on the wire.
FLEET_SERIES = [
    'fleet_requests_total{tenant="hot",outcome="admitted"}',
    'fleet_requests_total{tenant="cold",outcome="admitted"}',
    'fleet_replica_dispatch_total{replica="0",reason="least_loaded"}',
    'fleet_replica_dispatch_total{replica="0",reason="affinity"}',
    "fleet_queue_wait_seconds_bucket",
    "fleet_replicas_healthy",
    "fleet_queue_depth",
    # request-phase decomposition (ISSUE 12): the fleet smoke's
    # requests record per-phase spans, so every phase series carries
    # live values; the deadline'd request feeds the EDF-slack family
    'fleet_request_phase_seconds_bucket{phase="admission"',
    'fleet_request_phase_seconds_bucket{phase="placement"',
    'fleet_request_phase_seconds_bucket{phase="queue"',
    'fleet_request_phase_seconds_bucket{phase="prefill"',
    'fleet_request_phase_seconds_bucket{phase="decode"',
    'fleet_request_phase_seconds_bucket{phase="total"',
    'fleet_edf_slack_seconds_bucket{tenant="hot"',
]

# Fleet observability plane (ISSUE 12): asserted over the AGGREGATED
# 2-worker scrape (this process + a synthetic peer, both published as
# beacons and merged by FleetRegistry) — every entry must appear
# host-tagged AND rolled up.  ISSUE 13 widens the allowlist: the
# continuous device-phase profile must arrive host-tagged from BOTH
# workers with a fleet rollup, and the trace-store gauges must be on
# the same scrape.
FLEET_OBS_SERIES = [
    'generation_server_retired_total{host="workerA"}',
    'generation_server_retired_total{host="workerB"}',
    'generation_server_retired_total{host="fleet"}',
    'fleet_request_phase_seconds_bucket{phase="decode",host="fleet"',
    'fleet_requests_total{tenant="hot",outcome="admitted",host="fleet"}',
    'fleet_host_up{host="workerA"} 1.0',
    'fleet_host_up{host="workerB"} 1.0',
    "fleet_hosts_live 2.0",
    'fleet_beacon_publishes_total{host="workerA"}',
    # per-device continuous profiling (ISSUE 13): the real worker's
    # decode/prefill/verify samples + the synthetic peer's, each
    # host-tagged, plus the fleet rollup of the family
    'fleet_device_phase_seconds_count{device="cpu:0",'
    'phase="decode_tick",host="workerA"}',
    'fleet_device_phase_seconds_count{device="cpu:0",'
    'phase="prefill",host="workerA"}',
    'fleet_device_phase_seconds_count{device="cpu:0",'
    'phase="verify",host="workerA"}',
    'fleet_device_phase_seconds_count{device="cpu:0",'
    'phase="decode_tick",host="workerB"}',
    'fleet_device_phase_seconds_count{device="cpu:0",'
    'phase="decode_tick",host="fleet"}',
    # the on-demand XProf capture summary beacons fleet-wide (the raw
    # trace stays a host-local artifact)
    'fleet_xprof_captures_total{host="workerA"}',
    # cross-worker trace store: the aggregator's own gauges
    "fleet_trace_store_traces",
    "fleet_trace_store_spans",
    "fleet_trace_store_rooted",
]

# SLO error-budget engine (ISSUE 15): the induced-burn smoke below
# drives a synthetic outcome stream through a REAL AlertEngine
# attached to a FleetRegistry and scrapes the aggregated endpoint —
# the alert is observed FIRING on the wire (gauge 1.0 + the
# transitions counter), then RESOLVING once the bleeding stops.
# Asserted against the mid-burn FLEET scrape body, not the process
# registry (the engine exports into the aggregated view).
SLO_SERIES = [
    'fleet_slo_burn_rate{slo="smoke-avail",window="0.1s",'
    'host="fleet"}',
    'fleet_slo_burn_rate{slo="smoke-avail",window="0.3s",'
    'host="fleet"}',
    'fleet_slo_error_budget_remaining{slo="smoke-avail",'
    'host="fleet"}',
    'fleet_slo_alert_state{slo="smoke-avail",host="fleet"}',
    'fleet_slo_alert_firing{slo="smoke-avail",host="fleet"} 1.0',
    'fleet_slo_alert_transitions_total{slo="smoke-avail",'
    'to="firing",host="fleet"} 1',
]

# Production front door (ISSUE 18): the smoke below induces a REAL
# overload (100%-bad tenant traffic aged past the long burn window
# through a real AlertEngine), lets the attached DegradeLadder walk a
# real fleet up to rung 5 (admissions shaped, the batch class shed
# with a typed retry-after) and back to 0, and races one deadline'd
# request's hedge on the second replica — so the admission outcome
# counters, the rung gauge, the hedge race counters and the
# degrade-step flight events all carry live values on the wire.
DEGRADE_SERIES = [
    'fleet_admission_admitted_total{tenant="chat"}',
    'fleet_admission_degraded_total{tenant="chat"}',
    'fleet_admission_rejected_total{tenant="bulk"}',
    "fleet_degrade_rung",
    "fleet_hedges_launched_total",
    "fleet_hedges_won_total",
    "fleet_hedges_cancelled_total",
    'flight_events_total{kind="degrade_step"}',
    'flight_events_total{kind="hedge"}',
]

# Mesh-sharded serving (ISSUE 17): the smoke below decodes one prompt
# through a tp=2 replica spanning two virtual devices — byte-compared
# against the single-chip server — and constructs a mixed fleet, so
# the slice gauge, the tp-degree gauge, the forced reference_tp
# attention route and the PER-DEVICE phase attribution (one decode
# tick folds into EVERY chip of the slice) all carry live values.
MESH_SERIES = [
    'fleet_replica_devices{replica="0"} 1.0',
    'fleet_replica_devices{replica="1"} 2.0',
    "generation_server_tp_degree 2.0",
    'paged_route_total{path="reference_tp"}',
    'fleet_device_phase_seconds_count{device="cpu:1",'
    'phase="decode_tick"}',
]

# Flight recorder (ISSUE 15): the serve smokes above feed the
# process-default ring (admit/retire events), and the SLO section
# writes one explicit postmortem bundle — both families carry live
# values on the MAIN scrape.
FLIGHT_SERIES = [
    'flight_events_total{kind="admit"}',
    'flight_events_total{kind="retire"}',
    "postmortem_bundles_total",
]

# Embedded TSDB (ISSUE 16): every FleetRegistry records its view into
# its store per scrape, so the store's own accounting rides the
# AGGREGATED scrape (the SLO section's fleet endpoint asserts these).
TSDB_SERIES = [
    "fleet_tsdb_series",
    "fleet_tsdb_samples_total",
    "fleet_tsdb_evicted_total",
]

# Predictive-autoscaling series (ISSUE 13): the forecaster below runs
# a synthetic backlog ramp through the REAL fit/publish path, so the
# prediction gauges carry live values; chaos_smoke asserts the
# end-to-end pre-warm against a real ramp.
FORECAST_SERIES = [
    'fleet_autoscale_forecast{signal="slope"}',
    'fleet_autoscale_forecast{signal="backlog"}',
    'fleet_autoscale_forecast{signal="breach_s"}',
    "fleet_autoscale_prewarms_total",
]

#: one complete cross-component request trace must carry all of these
TRACE_PHASES = {"request", "request/admission", "request/placement",
                "request/replica_queue", "request/prefill",
                "request/decode"}

# Static-analysis subsystem series: the lint counter gets labeled
# children from emit_analysis_series() below, which also runs a real
# (small) package-index build so the whole-package-mode series carry
# live values; sanitizer_trips_total is registered by importing the
# training stack (its HELP/TYPE lines are always on the wire;
# chaos_smoke additionally fires a real trip).
ANALYSIS_SERIES = [
    'lint_findings_total{rule="JIT101",severity="error"}',
    "sanitizer_trips_total",
    "lint_modules_indexed_total",
    "lint_runtime_seconds_bucket",
]

# one deliberate trace-safety violation — linting it populates
# lint_findings_total{rule=,severity=} without walking the whole tree
ANALYSIS_FIXTURE = (
    "import time\n"
    "import jax\n"
    "@jax.jit\n"
    "def f(x):\n"
    "    t = time.time()\n"
    "    return x * t\n")


def emit_analysis_series(problems) -> None:
    """Lint the known-bad fixture and count the findings into the
    process registry (the CLI's --telemetry hook, in-process) — shared
    with chaos_smoke so both reports cover the analysis subsystem.
    Also builds a real (small) package index over the analysis
    subpackage itself so the whole-package-mode series
    (lint_modules_indexed_total / lint_runtime_seconds) carry live
    values on the wire."""
    from deeplearning4j_tpu.analysis import jit_lint, package_index
    from deeplearning4j_tpu.analysis.cli import emit_telemetry
    findings = jit_lint.lint_source(ANALYSIS_FIXTURE, "<fixture>")
    if not any(f.rule == "JIT101" for f in findings):
        problems.append(
            "analysis fixture produced no JIT101 finding "
            f"(got {[f.rule for f in findings]})")
    emit_telemetry(findings)
    pkg = os.path.join(os.path.dirname(package_index.__file__))
    _, _, stats = package_index.build_index(pkg, root=os.path.dirname(
        os.path.dirname(pkg)))
    if stats.modules < 5:
        problems.append(
            f"package index over analysis/ saw {stats.modules} modules")
    package_index.emit_index_telemetry(stats)


def assert_live_lock_order(problems, cache_path=None) -> None:
    """Build the lock-order graph of the LIVE serving configuration —
    the fleet scheduler, degrade-ladder clock, autoscaler, alert
    engine and TSDB recorder threads all live under ``serving/`` +
    ``telemetry/`` — and assert it is ACYCLIC (ISSUE 19): a CONC301
    cycle there is a latent production deadlock, so the chaos run
    fails on it rather than leaving it to the lint gate.  The pass
    runtime lands in ``lint_runtime_seconds`` and the cycle count on
    the ``lint_lock_graph_cycles`` gauge so the scrape proves the
    probe ran."""
    import time as _time
    from deeplearning4j_tpu import telemetry
    from deeplearning4j_tpu.analysis import lock_order, package_index
    pkgroot = os.path.dirname(os.path.dirname(package_index.__file__))
    root = os.path.dirname(pkgroot)
    t0 = _time.perf_counter()
    merged, stats = {}, package_index.IndexStats()
    for sub in ("serving", "telemetry"):
        idx, _, st = package_index.build_index(
            os.path.join(pkgroot, sub), root=root,
            cache_path=cache_path, run_local_passes=False)
        merged.update(idx.modules)
        stats.modules += st.modules
        stats.cache_hits += st.cache_hits
    live = package_index.PackageIndex(merged)
    cycles = [f for f in lock_order.lint_package(live)
              if f.rule == "CONC301"]
    stats.elapsed_s = _time.perf_counter() - t0
    for f in cycles:
        problems.append(
            f"lock-order CYCLE in the live serving config: {f.message}")
    if stats.modules < 10:
        problems.append("live lock-order probe indexed only "
                        f"{stats.modules} modules")
    telemetry.gauge(
        "lint_lock_graph_cycles",
        "CONC301 cycles in the live serving configuration's "
        "lock-order graph (must be 0)").set(len(cycles))
    package_index.emit_index_telemetry(stats)


def scrape_body(telemetry, registry) -> str:
    """Serve one scrape over a real HTTP endpoint and return the
    Prometheus text body (shared with chaos_smoke)."""
    with telemetry.start_metrics_server(registry, port=0) as srv:
        return urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=5
        ).read().decode()


def missing_series(body: str, required) -> list:
    return [f"required series missing: {needle!r}"
            for needle in required if needle not in body]


def main() -> int:
    from deeplearning4j_tpu import (MultiLayerNetwork,
                                    NeuralNetConfiguration, telemetry)
    from deeplearning4j_tpu import kernels
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterator import ListDataSetIterator
    from deeplearning4j_tpu.nn.conf.layers_core import (DenseLayer,
                                                        OutputLayer)
    from deeplearning4j_tpu.optimize.updaters import Adam
    from deeplearning4j_tpu.parallel import ParallelInference
    from deeplearning4j_tpu.ui import InMemoryStatsStorage, render_report

    import jax.numpy as jnp

    registry = telemetry.get_registry()
    tracer = telemetry.get_tracer()
    problems = []

    # -- train: 5 iterations with the telemetry listener ---------------
    conf = (NeuralNetConfiguration.builder().seed(7)
            .updater(Adam(learning_rate=1e-2)).list()
            .layer(DenseLayer(n_in=8, n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=4, activation="softmax",
                               loss="mcxent"))
            .build())
    model = MultiLayerNetwork(conf).init()
    storage = InMemoryStatsStorage()
    # ~2*params*3 train FLOPs/example for the 8-16-4 MLP — real enough
    # for the mfu gauge to be a number, which is all a smoke asserts
    flops = 2 * 3 * (8 * 16 + 16 * 4)
    model.set_listeners(telemetry.TelemetryListener(
        storage=storage, flops_per_example=flops, peak_flops=1e12))
    rng = np.random.default_rng(0)
    x = rng.normal(size=(5 * 32, 8)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, len(x))]
    model.fit(ListDataSetIterator(DataSet(x, y).batch_by(32)), n_epochs=1)

    # -- touch the kernel router so flash_route_total has a child ------
    q = jnp.asarray(rng.normal(size=(1, 2, 8, 4)), jnp.float32)
    kernels.attention(q, q, q)

    # -- serve: 16 concurrent requests ---------------------------------
    # the registry is process-global (tests may have served already):
    # assert the DELTA this run contributes
    lat = registry.histogram("inference_latency_seconds")
    lat_before = lat.count
    xs = [rng.normal(size=(8,)).astype(np.float32) for _ in range(16)]
    with ParallelInference(model, batch_limit=8, timeout_ms=5) as pi:
        errs = []

        def call(i):
            try:
                pi.output(xs[i])
            except Exception as e:  # pragma: no cover - smoke surface
                errs.append(f"request {i}: {e}")

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        problems += errs

    # -- serve decode: 3 requests through 2 slots (exercises the
    # continuous-batching queue) -------------------------------------
    from deeplearning4j_tpu.parallel import GenerationServer
    from deeplearning4j_tpu.zoo.gpt import Gpt

    retired = registry.counter("generation_server_retired_total")
    syncs = registry.counter("generation_server_host_syncs_total")
    retired_before = retired.value
    gpt = Gpt(vocab_size=50, max_len=32, d_model=32, n_layers=2,
              n_heads=4, d_ff=64, seq_len=8, compute_dtype=None,
              seed=3).init_graph()
    with GenerationServer(gpt, n_slots=2, max_len=32) as gs:
        gh = [gs.submit_async(np.asarray([1, 2, 3, 4], np.int32),
                              n_new=4) for _ in range(3)]
        for i, handle in enumerate(gh):
            try:
                out = handle.result(timeout=300)
                if out.shape != (8,):
                    problems.append(
                        f"generation request {i}: shape {out.shape}")
            except Exception as e:  # pragma: no cover - smoke surface
                problems.append(f"generation request {i}: {e}")
        # one solo request with an empty queue: the scheduler must
        # fuse its 4 ticks into ONE lax.scan dispatch (k=4) and poll
        # the host once for it.  The on-demand XProf trigger is armed
        # around it: the next measured dispatch runs under a REAL
        # jax.profiler capture whose summary lands on the registry
        # (and so on every beacon) while the raw trace stays local.
        prof = telemetry.get_profiler()
        xprof_captures = registry.counter("fleet_xprof_captures_total")
        xc0 = xprof_captures.value
        syncs_before = syncs.value
        with tempfile.TemporaryDirectory() as xprof_dir:
            prof.request_xprof(xprof_dir, dispatches=1)
            try:
                gs.submit(np.asarray([4, 3, 2, 1], np.int32), n_new=4,
                          timeout=300)
            except Exception as e:  # pragma: no cover - smoke surface
                problems.append(f"solo scan request: {e}")
        if syncs.value - syncs_before != 1:
            problems.append(
                f"solo 4-token request cost {syncs.value - syncs_before}"
                " host syncs (expected 1 fused k=4 scan)")
        if xprof_captures.value - xc0 != 1:
            problems.append("on-demand XProf trigger did not complete "
                            "exactly one capture")
        if registry.gauge("fleet_xprof_capture_files").value < 1:
            problems.append("XProf capture summary reports no files "
                            "written")
    if retired.value - retired_before != 4:
        problems.append(f"generation_server_retired_total grew "
                        f"{retired.value - retired_before} != 4")

    # -- paged KV: two requests sharing one system prompt must score a
    # real prefix-cache hit (the second prefills only its suffix) ----
    hits = registry.counter("prefix_cache_hits_total")
    shared = registry.counter("kv_blocks_shared_total")
    hits_before, shared_before = hits.value, shared.value
    sys_prompt = np.asarray([3, 1, 4, 1, 5, 9, 2, 6, 5], np.int32)
    with GenerationServer(gpt, n_slots=2, max_len=32,
                          block_size=4) as gs2:
        out_a = gs2.submit(sys_prompt, n_new=4, timeout=300)
        out_b = gs2.submit(sys_prompt, n_new=4, timeout=300)
    if hits.value - hits_before < 1:
        problems.append("two same-system-prompt requests produced no "
                        "prefix_cache_hits_total increment")
    if shared.value - shared_before < 1:
        problems.append("prefix hit mapped no shared blocks "
                        "(kv_blocks_shared_total flat)")
    if not np.array_equal(out_a, out_b):
        problems.append("prefix-hit decode diverged from the cold "
                        "decode of the same prompt")

    # -- tiered KV: a tier-backed server whose pool is too small for
    # two working sets — the second distinct prompt EVICTS the first's
    # cached blocks (spill to host RAM), the first's re-admission
    # restores them with one batched H2D (tier fetch), outputs
    # identical; then the prefix hands off to a SECOND server
    # (export -> import) whose admission tier-fetches it ------------
    t_spills = registry.counter("kv_tier_spills_total")
    t_fetches = registry.counter("kv_tier_fetches_total")
    t_handoff = registry.counter("kv_handoff_blocks_total")
    ts0, tf0, th0 = t_spills.value, t_fetches.value, t_handoff.value
    tp_a = np.asarray([3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9],
                      np.int32)
    tp_b = np.asarray([2, 7, 1, 8, 2, 8, 1, 8, 2, 8, 4, 5, 9],
                      np.int32)
    with GenerationServer(gpt, n_slots=2, max_len=32, block_size=4,
                          kv_blocks=8, host_tier_blocks=8,
                          tick_timeout_s=None) as gt:
        tier_a = gt.submit(tp_a, n_new=12, timeout=300)
        gt.submit(tp_b, n_new=12, timeout=300)     # evicts A -> spill
        if t_spills.value - ts0 < 1:
            problems.append("tier-sized-down pool produced no "
                            "kv_tier_spills_total increment")
        tier_a2 = gt.submit(tp_a, n_new=12, timeout=300)  # tier fetch
        if t_fetches.value - tf0 < 1:
            problems.append("re-admission of the spilled prefix "
                            "produced no kv_tier_fetches_total "
                            "increment")
        if not np.array_equal(tier_a, tier_a2):
            problems.append("tier-fetch decode diverged from the cold "
                            "decode of the same prompt")
        handoff_payload = gt.export_prefix(tp_a)
    if len(handoff_payload) != 3:
        problems.append(f"export_prefix returned "
                        f"{len(handoff_payload)} blocks, expected 3")
    with GenerationServer(gpt, n_slots=2, max_len=32, block_size=4,
                          tick_timeout_s=None) as gi:
        gi.import_blocks(handoff_payload)
        tier_a3 = gi.submit(tp_a, n_new=12, timeout=300)
        if not np.array_equal(tier_a, tier_a3):
            problems.append("handed-off decode diverged from the "
                            "origin server's decode")
        if gi.stats()["tier_fetches"] < 1:
            problems.append("handoff admission restored no tier "
                            "blocks on the importing server")
    if t_handoff.value - th0 != 3:
        problems.append("kv_handoff_blocks_total grew "
                        f"{t_handoff.value - th0} != 3")

    # -- speculative decode: a draft-verified server must agree with
    # the plain server byte-for-byte AND count real proposals -------
    spec_prop = registry.counter(
        "generation_server_spec_proposed_total")
    spec_acc = registry.counter(
        "generation_server_spec_accepted_total")
    sp0, sa0 = spec_prop.value, spec_acc.value
    spec_prompt = np.asarray([2, 7, 1, 8, 2, 8], np.int32)
    with GenerationServer(gpt, n_slots=2, max_len=32,
                          tick_timeout_s=None) as gp:
        ref_out = gp.submit(spec_prompt, n_new=6, timeout=300)
    with GenerationServer(gpt, n_slots=2, max_len=32,
                          tick_timeout_s=None,
                          speculative={"k": 2, "rounds": 2,
                                       "draft_layers": 2}) as gs3:
        spec_out = gs3.submit(spec_prompt, n_new=6, timeout=300)
        spec_stats = gs3.stats()
    if not np.array_equal(spec_out, ref_out):
        problems.append("speculative decode diverged from the "
                        "non-speculative decode of the same prompt")
    if spec_prop.value - sp0 < 1:
        problems.append("speculative decode proposed no draft tokens "
                        "(generation_server_spec_proposed_total flat)")
    if spec_acc.value - sa0 != spec_prop.value - sp0:
        problems.append(
            "full-depth self-draft must accept every proposal "
            f"(accepted {spec_acc.value - sa0} != proposed "
            f"{spec_prop.value - sp0})")
    if spec_stats["spec_acceptance_rate"] != 1.0:
        problems.append("per-instance spec acceptance rate "
                        f"{spec_stats['spec_acceptance_rate']} != 1.0")

    # -- sampled speculative decode + adaptive K (ISSUE 20): a
    # tenant-tagged SAMPLED request through an adaptive-depth server
    # puts the adaptive-K gauge and the per-tenant acceptance series
    # on the scrape with real post-dispatch values ------------------
    adaptive_k = registry.gauge("generation_server_spec_adaptive_k")
    with GenerationServer(gpt, n_slots=2, max_len=32,
                          tick_timeout_s=None,
                          speculative={"k": 2, "rounds": 2,
                                       "draft_layers": 2,
                                       "adaptive": True,
                                       "k_max": 3}) as ga:
        samp_out = ga.submit(spec_prompt, n_new=6, sampling={
            "temperature": 0.8, "top_k": 8, "seed": 5},
            tenant="spec-tenant", timeout=300)
        ctl_snap = ga._spec_ctl.snapshot()
    if samp_out.shape != (12,) or not (
            (samp_out >= 0).all() and (samp_out < 50).all()):
        problems.append("sampled speculative decode returned a "
                        f"malformed stream (shape {samp_out.shape})")
    if not 1 <= adaptive_k.value <= 3:
        problems.append("generation_server_spec_adaptive_k "
                        f"{adaptive_k.value} outside [1, k_max=3]")
    if ctl_snap["global_proposed"] < 1:
        problems.append("acceptance controller observed no "
                        "proposals from the sampled spec decode")
    tenant_rate = registry.gauge(
        "generation_server_tenant_spec_acceptance_rate",
        labelnames=("tenant",)).labels(tenant="spec-tenant")
    if not 0.0 <= tenant_rate.value <= 1.0:
        problems.append("per-tenant spec acceptance rate "
                        f"{tenant_rate.value} outside [0, 1]")

    # -- serving fleet: 2 replicas x 2 tenants through the admission
    # router — the repeated hot-tenant prompt must ride affinity to
    # the warm replica and score a real prefix hit THERE -------------
    from deeplearning4j_tpu.serving import ServingFleet

    with ServingFleet(gpt, n_replicas=2, n_slots=2, max_len=32,
                      block_size=4, tick_batch=1,
                      tick_timeout_s=None) as fleet:
        fp = np.asarray([2, 7, 1, 8, 2, 8, 1, 8, 2], np.int32)
        out_hot = fleet.submit(fp, n_new=4, tenant="hot", timeout=300)
        # deadline'd so the EDF-slack histogram records at dispatch
        fh = fleet.submit_async(fp, n_new=4, tenant="hot",
                                deadline_s=300.0)
        out_rep = fh.result(timeout=300)
        out_cold = fleet.submit(np.asarray([6, 5, 4, 3], np.int32),
                                n_new=4, tenant="cold", timeout=300)
        if out_cold.shape != (8,):
            problems.append(
                f"fleet cold-tenant request: shape {out_cold.shape}")
        if not np.array_equal(out_hot, out_rep):
            problems.append("fleet repeat decode diverged from its "
                            "first decode of the same prompt")
        if fh.replica is None or \
                fleet.replica(fh.replica).stats()["prefix_hits"] < 1:
            problems.append("fleet affinity repeat scored no prefix "
                            "hit on the warm replica")
        if fleet.stats()["healthy_replicas"] != 2:
            problems.append("fleet not fully healthy after the smoke")
        fleet_trace_id = fh.trace_id

    # -- request-scoped tracing: the deadline'd request must have ONE
    # complete cross-component trace (submit -> retire, every phase
    # span stamped with its fleet-minted trace id) ------------------
    tr_names = {e["name"]
                for e in tracer.events_for_trace(fleet_trace_id)}
    if not TRACE_PHASES <= tr_names:
        problems.append(
            f"request trace {fleet_trace_id} incomplete: missing "
            f"{sorted(TRACE_PHASES - tr_names)}")
    if tracer.open_spans():
        problems.append(
            "tracked spans left open after every request retired: "
            f"{[s.name for s in tracer.open_spans()]}")

    # -- production front door (ISSUE 18): induce a REAL overload —
    # all-bad tenant traffic aged past the long burn window drives
    # the engine's admission projection, the attached ladder walks a
    # real 2-replica fleet to rung 5 (budgets capped, batch shed with
    # retry-after) and back down once the burn clears, and a
    # deadline'd request under hedge_slack_s races a hedge ---------
    from deeplearning4j_tpu.serving import (AdmissionRejectedError,
                                            DegradeLadder, TenantQuota)
    from deeplearning4j_tpu.telemetry.slo import AlertEngine, SLOSpec
    dreg = telemetry.MetricsRegistry()
    dfam = dreg.counter("fleet_requests_total",
                        labelnames=("tenant", "outcome"))
    deg_eng = AlertEngine(
        [SLOSpec("smoke-degrade", target=0.9, tenant="bulk",
                 window_s=600.0, windows=[(0.1, 0.3, 1.5, "page")])],
        source=dreg, registry=telemetry.MetricsRegistry())
    deg_eng.evaluate(now=0.0)            # prime the history
    for t in (0.2, 0.4, 0.6):            # 100% bad, past the 0.3s
        dfam.labels(tenant="bulk", outcome="failed").inc(5)
        deg_eng.evaluate(now=t)          # long window: burn 10x
    hlaunch = registry.counter("fleet_hedges_launched_total")
    hcancel = registry.counter("fleet_hedges_cancelled_total")
    hl0, hc0 = hlaunch.value, hcancel.value
    with ServingFleet(gpt, n_replicas=2, n_slots=2, max_len=32,
                      block_size=4, tick_batch=1, tick_timeout_s=None,
                      hedge_slack_s=60.0,
                      quotas={"bulk": TenantQuota(klass="batch")}
                      ) as dfleet:
        lad = DegradeLadder(dfleet, deg_eng,
                            thresholds=(1.0, 2.0, 3.0, 4.0, 5.0),
                            hold_down_s=0.0)
        dfleet.attach_degrade(lad)
        rung = lad.evaluate(now=0.6)     # real projection read
        if rung != 5:
            problems.append(f"induced 10x burn drove the ladder to "
                            f"rung {rung}, expected 5")
        try:
            dfleet.submit_async(np.asarray([1, 2, 3], np.int32), 4,
                                tenant="bulk")
            problems.append("batch tenant admitted during the "
                            "overload (rung 5 must shed)")
        except AdmissionRejectedError as e:
            if not e.retry_after_s > 0:
                problems.append("shed batch tenant carried no "
                                "retry_after_s hint")
        deg_out = dfleet.submit(np.asarray([5, 6, 7], np.int32), 8,
                                tenant="chat", timeout=300)
        if deg_out.shape != (5,):        # n_new 8 -> capped 2
            problems.append(f"rung 5 did not cap n_new: shape "
                            f"{deg_out.shape}, expected (5,)")
        for i in range(12):              # the burn cleared: walk down
            rung = lad.evaluate(now=10.0 + i)
            if rung == 0:
                break
        if rung != 0:
            problems.append("ladder did not walk back to rung 0 "
                            "after the burn cleared")
        full_out = dfleet.submit(np.asarray([5, 6, 7], np.int32), 8,
                                 tenant="chat", timeout=300)
        if full_out.shape != (11,):
            problems.append("post-recovery request still degraded: "
                            f"shape {full_out.shape}, expected (11,)")
        hh = dfleet.submit_async(np.asarray([1, 2, 3, 4], np.int32),
                                 8, tenant="chat", deadline_s=30.0)
        hh.result(timeout=300)
        hedge_deadline = time.monotonic() + 30
        while time.monotonic() < hedge_deadline:
            if (hlaunch.value - hl0 >= 1
                    and hcancel.value - hc0 == hlaunch.value - hl0):
                break
            time.sleep(0.01)
        if hlaunch.value - hl0 < 1:
            problems.append("deadline'd request under hedge_slack_s "
                            "launched no hedge")
        elif hcancel.value - hc0 != hlaunch.value - hl0:
            problems.append(
                "hedge race left unresolved: launched "
                f"{hlaunch.value - hl0} != cancelled "
                f"{hcancel.value - hc0}")

    # -- predictive autoscaling: a synthetic backlog ramp through the
    # REAL forecaster fit/publish path — the prediction gauges carry
    # live values on the scrape, and the math is checked against the
    # known ramp (backlog = 2t, threshold 20, at t=5 -> breach in 5s)
    from deeplearning4j_tpu.serving import BacklogForecaster
    fc = BacklogForecaster(window_s=60.0, min_points=4)
    for t in range(6):
        fc.observe(float(t), 2.0 * t)
    breach = fc.breach_s(20.0)
    if breach is None or abs(breach - 5.0) > 1e-6:
        problems.append(f"forecast on the synthetic ramp predicted "
                        f"{breach}s to breach, expected 5.0s")
    # the prewarm counter exists on every process that imports the
    # autoscaler (unlabeled counter exposes at 0; chaos_smoke asserts
    # the live pre-warm)
    registry.counter("fleet_autoscale_prewarms_total")

    # -- fleet observability plane: TWO workers' beacons aggregate
    # into ONE scrape with {host=} tags and fleet rollups; the same
    # beacons carry closed request spans the aggregator's trace store
    # stitches into ONE submit -> retire tree per request ------------
    worker_b = telemetry.MetricsRegistry()
    worker_b.counter("generation_server_retired_total").inc(2)
    worker_b.counter("fleet_requests_total",
                     labelnames=("tenant", "outcome")).labels(
                         tenant="hot", outcome="admitted").inc(3)
    worker_b.histogram("fleet_device_phase_seconds",
                       labelnames=("device", "phase")).labels(
                           device="cpu:0",
                           phase="decode_tick").observe(0.003)
    with tempfile.TemporaryDirectory() as d:
        with telemetry.MetricsBeacon(d, host="workerA",
                                     interval_s=60.0):
            pass                 # start + final publish
        telemetry.publish_beacon(d, "workerB", registry=worker_b)
        fleet_view = telemetry.FleetRegistry(d, stale_after_s=3600.0)
        with telemetry.start_metrics_server(fleet_view, port=0) as srv:
            obs_body = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=5
            ).read().decode()
            tr_body = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/traces?id="
                f"{fleet_trace_id}", timeout=5).read().decode()
    problems += missing_series(obs_body, FLEET_OBS_SERIES)
    tree = json.loads(tr_body)
    if not tree.get("root") or tree["root"]["name"] != "request":
        problems.append("fleet trace store has no stitched root for "
                        f"trace {fleet_trace_id}")
    else:
        def _names(node):
            out = {node["name"]}
            for c in node["children"]:
                out |= _names(c)
            return out
        got = _names(tree["root"])
        if not {"request/admission", "request/prefill",
                "request/decode"} <= got:
            problems.append(
                f"stitched fleet trace missing phases: {sorted(got)}")
        if tree["orphans"]:
            problems.append("stitched fleet trace left orphan "
                            f"fragments: {tree['orphans']}")
    retired_roll = retired.value + 2
    for line in obs_body.splitlines():
        if line.startswith('generation_server_retired_total'
                           '{host="fleet"} '):
            if float(line.rsplit(" ", 1)[1]) != retired_roll:
                problems.append(
                    "fleet rollup retired_total "
                    f"{line.rsplit(' ', 1)[1]} != sum of workers "
                    f"{retired_roll}")
            break

    # -- elastic fleet resume: a checkpoint recorded at world=2 is
    # fleet-resumed at world=1, so the shrink counter, world gauge and
    # rendezvous-wait histogram carry REAL values on the scrape ------
    from deeplearning4j_tpu.parallel import CheckpointListener
    from deeplearning4j_tpu.resilience import fleet_resume_fit

    elastic = registry.counter("fleet_elastic_resumes_total",
                               labelnames=("direction",))
    shrink0 = elastic.labels(direction="shrink").value
    with tempfile.TemporaryDirectory() as d:
        em = MultiLayerNetwork(conf).init()
        ck = CheckpointListener(os.path.join(d, "ck"),
                                save_every_n_iterations=2,
                                async_save=False, world=2)
        em.set_listeners(ck)
        em.fit(ListDataSetIterator(DataSet(x, y).batch_by(32)),
               n_epochs=1, async_prefetch=False)
        fleet_resume_fit(
            lambda: em.fit(ListDataSetIterator(DataSet(x, y).batch_by(32)),
                           n_epochs=2, resume=True,
                           async_prefetch=False),
            checkpoint=ck, world=1)
        ck.ckpt.close()
    if elastic.labels(direction="shrink").value - shrink0 < 1:
        problems.append("world=2 checkpoint fleet-resumed at world=1 "
                        "counted no elastic shrink")

    # -- SLO error-budget engine (ISSUE 15): an induced burn must be
    # observed FIRING on a real aggregated scrape, then RESOLVING
    # once the bleeding stops; one explicit postmortem bundle proves
    # the flight-recorder dump path end to end --------------------
    from deeplearning4j_tpu.telemetry.slo import AlertEngine, SLOSpec
    sreg = telemetry.MetricsRegistry()
    sfam = sreg.counter("fleet_requests_total",
                        labelnames=("tenant", "outcome"))
    sfam.labels(tenant="smoke", outcome="admitted")
    sfam.labels(tenant="smoke", outcome="failed")
    slo_eng = AlertEngine(
        [SLOSpec("smoke-avail", target=0.9, window_s=600.0,
                 windows=[(0.1, 0.3, 1.5, "page")])],
        registry=telemetry.MetricsRegistry())
    with tempfile.TemporaryDirectory() as d:
        telemetry.publish_beacon(d, "slohost", registry=sreg)
        fview = telemetry.FleetRegistry(d, stale_after_s=3600.0,
                                        alerts=slo_eng)
        with telemetry.start_metrics_server(fview, port=0) as srv:
            base = f"http://127.0.0.1:{srv.port}"
            urllib.request.urlopen(base + "/metrics",
                                   timeout=5).read()   # primes
            sfam.labels(tenant="smoke", outcome="failed").inc(9)
            sfam.labels(tenant="smoke", outcome="admitted").inc(1)
            telemetry.publish_beacon(d, "slohost", registry=sreg)
            time.sleep(0.35)           # long-window coverage accrues
            slo_body = urllib.request.urlopen(
                base + "/metrics", timeout=5).read().decode()
            alerts_doc = json.loads(urllib.request.urlopen(
                base + "/alerts", timeout=5).read().decode())
            problems += missing_series(slo_body, SLO_SERIES)
            if alerts_doc.get("firing") != ["smoke-avail"]:
                problems.append("induced burn not firing at /alerts: "
                                f"{alerts_doc.get('firing')}")
            # the bleeding stops: clean traffic must RESOLVE it
            sfam.labels(tenant="smoke", outcome="admitted").inc(500)
            telemetry.publish_beacon(d, "slohost", registry=sreg)
            time.sleep(0.35)
            alerts_doc = json.loads(urllib.request.urlopen(
                base + "/alerts", timeout=5).read().decode())
            states = {a["slo"]: a["state"]
                      for a in alerts_doc.get("alerts", ())}
            if states.get("smoke-avail") != "resolved":
                problems.append("induced burn did not resolve after "
                                f"clean traffic: {states}")
            # ISSUE 16: the store's accounting on the aggregated
            # scrape, and a live /query over the recorded history —
            # the admitted counter's rate must be positive and
            # consistent with its delta over the same window
            problems += missing_series(slo_body, TSDB_SERIES)
            qbase = (base + "/query?series=fleet_requests_total"
                     "&tenant=smoke&outcome=admitted")
            qr = json.loads(urllib.request.urlopen(
                qbase, timeout=5).read().decode())
            pts = [p for r in qr.get("results", ())
                   for p in r.get("points", ())]
            if len(pts) < 2:
                problems.append("/query range over the admitted "
                                f"counter held {len(pts)} samples "
                                f"(< 2): {qr}")
            qd = json.loads(urllib.request.urlopen(
                qbase + "&func=delta", timeout=5).read().decode())
            qrt = json.loads(urllib.request.urlopen(
                qbase + "&func=rate", timeout=5).read().decode())
            dv = [r["value"] for r in qd.get("results", ())
                  if r.get("value") is not None]
            rv = [r["value"] for r in qrt.get("results", ())
                  if r.get("value") is not None]
            if not dv or dv[0] <= 0:
                problems.append("/query delta over the admitted "
                                f"counter not positive: {qd}")
            if not rv or rv[0] <= 0:
                problems.append("/query rate over the admitted "
                                f"counter not positive: {qrt}")
            if dv and rv and len(pts) >= 2:
                span = pts[-1][0] - pts[0][0]
                if span > 0 and (abs(rv[0] * span - dv[0])
                                 > 1e-6 + 0.1 * abs(dv[0])):
                    problems.append(
                        f"/query rate {rv[0]:g} inconsistent with "
                        f"delta {dv[0]:g} over {span:.3f}s")
            try:
                urllib.request.urlopen(base + "/query?series=",
                                       timeout=5)
                problems.append("/query with an empty series "
                                "selector did not answer 400")
            except urllib.error.HTTPError as e:
                if e.code != 400:
                    problems.append("/query with an empty series "
                                    f"selector answered {e.code}")
        # one explicit postmortem bundle: the dump path end to end
        recorder = telemetry.get_flight_recorder()
        recorder.install_dump(d, host="smokehost", alerts=slo_eng)
        bundle_path = recorder.request_dump("check_telemetry smoke")
        recorder.uninstall_dump()
        from deeplearning4j_tpu.telemetry import flightrec
        if bundle_path is None or flightrec.list_bundles(d) != [
                bundle_path]:
            problems.append("explicit request_dump produced no "
                            "postmortem bundle")
        else:
            bdoc = flightrec.load_bundle(bundle_path)
            if not bdoc.get("events"):
                problems.append("postmortem bundle carries no "
                                "flight-recorder events")
            if (bdoc.get("slo") or {}).get("specs") != 1:
                problems.append("postmortem bundle carries no SLO "
                                "state")

    # -- mesh-sharded serving (ISSUE 17): a tp=2 replica over two
    # virtual devices must decode byte-identical to the single-chip
    # server, report the GLOBAL pool's block counts (the autoscaler /
    # placement view), and attribute its decode phase to EVERY chip of
    # the slice; a mixed fleet puts the per-replica slice gauge on the
    # wire ----------------------------------------------------------
    import jax
    if jax.device_count() < 2:
        problems.append(f"mesh smoke needs >= 2 devices, have "
                        f"{jax.device_count()}")
    else:
        tp_slice = jax.devices()[:2]
        mp = np.asarray([3, 1, 4, 1, 5, 9], np.int32)
        with GenerationServer(gpt, n_slots=2, max_len=32) as gm0:
            mesh_ref = gm0.submit(mp, n_new=4, timeout=300)
            free_plain = gm0.stats()["free_blocks"]
        with GenerationServer(gpt, n_slots=2, max_len=32,
                              devices=tp_slice) as gm:
            mesh_out = gm.submit(mp, n_new=4, timeout=300)
            mst = gm.stats()
        if not np.array_equal(mesh_out, mesh_ref):
            problems.append("tp=2 decode diverged from the "
                            "single-chip decode of the same prompt")
        if mst["tp"] != 2 or mst["devices"] != [
                f"{d.platform}:{d.id}" for d in tp_slice]:
            problems.append(f"sharded server stats misreport the "
                            f"slice: tp={mst['tp']} "
                            f"devices={mst['devices']}")
        if mst["free_blocks"] != free_plain:
            problems.append(
                "sharded pool free-KV view is not the GLOBAL block "
                f"count ({mst['free_blocks']} != {free_plain}) — the "
                "autoscaler would see a per-shard fraction")
        # mixed fleet: single-chip replica 0 + tp=2 replica 1 — the
        # slice gauge needs no traffic, it is set at construction
        with ServingFleet(gpt, n_replicas=2, n_slots=2, max_len=32,
                          devices=[None, tp_slice]):
            pass

    # -- static analysis: lint series on the wire ----------------------
    emit_analysis_series(problems)

    # -- scrape over HTTP ----------------------------------------------
    body = scrape_body(telemetry, registry)

    series = {line.rsplit(" ", 1)[0] for line in body.splitlines()
              if line and not line.startswith("#")}
    if len(series) < 20:
        problems.append(f"only {len(series)} series exposed (< 20)")
    for fam in registry.families():
        if fam.kind != "histogram":
            continue
        for lv, child in fam._items():
            s = child.state()[2]
            if math.isnan(s):
                problems.append(f"histogram {fam.name}{lv} sum is NaN")
    required = [
        'inference_latency_seconds_bucket',
        'flash_route_total{path="xla"}',
        "mfu ",
        "train_data_wait_seconds_bucket",
        "train_step_dispatch_seconds_bucket",
        "generation_server_admitted_total",
        "generation_server_retired_total",
        "generation_server_ttft_seconds_bucket",
        "generation_server_slots_busy",
        "generation_server_slot_occupancy_bucket",
        "generation_server_ticks_total",
        # multi-tick decode scan series: the solo request above
        # guarantees a k=4 fused scan ran and was host-polled once
        "generation_server_host_syncs_total",
        'generation_server_scan_ticks_total{k="4"}',
        "generation_server_tokens_per_dispatch",
        # continuous device-phase profile (ISSUE 13): the serve/spec
        # runs above sampled all three serve phases on this process
        'fleet_device_phase_seconds_bucket{device="cpu:0",'
        'phase="decode_tick"',
        'fleet_device_phase_seconds_bucket{device="cpu:0",'
        'phase="prefill"',
        'fleet_device_phase_seconds_bucket{device="cpu:0",'
        'phase="verify"',
        "fleet_xprof_captures_total",
        "fleet_xprof_capture_files",
    ] + PAGED_KV_SERIES + TIERED_KV_SERIES + SPEC_SERIES \
      + FLEET_SERIES + RESILIENCE_SERIES + ANALYSIS_SERIES \
      + FORECAST_SERIES + FLIGHT_SERIES + MESH_SERIES \
      + DEGRADE_SERIES
    problems += missing_series(body, required)
    if lat.count - lat_before != 16:
        problems.append(
            f"latency histogram grew {lat.count - lat_before} != 16")

    # -- trace export + report embedding -------------------------------
    with tempfile.TemporaryDirectory() as d:
        trace = tracer.export_jsonl(os.path.join(d, "trace.jsonl"))
        if os.path.getsize(trace) == 0:
            problems.append("span trace export is empty")
        out = render_report(storage, os.path.join(d, "report.html"),
                            trace_path="trace.jsonl")
        html = open(out).read() if out else ""
        if "Telemetry" not in html or "trace.jsonl" not in html:
            problems.append("report missing telemetry table or trace link")

    print(json.dumps({"ok": not problems, "series": len(series),
                      "problems": problems}))
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
