#!/usr/bin/env python
"""Train + publish the in-repo pretrained weight sets
(``zoo/weights/*.zip`` + sha256 manifests) — the stand-in for
upstream's blob-hosted ``ZooModel.pretrainedUrl`` table (no egress in
this environment; the synthetic-MNIST caveat from ``data/mnist.py``
applies to the reported accuracies).

Run from the repo root:  python scripts/train_pretrained.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


class ImageMnist:
    """Flat [b, 784] MNIST reshaped to NHWC images for conv models."""

    def __init__(self, inner):
        self.inner = inner

    def __iter__(self):
        from deeplearning4j_tpu.data.dataset import DataSet
        for ds in self.inner:
            yield DataSet(
                np.asarray(ds.features).reshape(-1, 28, 28, 1),
                ds.labels)

    def reset(self):
        self.inner.reset()


def train_lenet(out_dir):
    from deeplearning4j_tpu.data.mnist import MnistDataSetIterator
    from deeplearning4j_tpu.zoo import LeNet, save_pretrained
    from deeplearning4j_tpu.optimize.updaters import Adam

    model = LeNet(n_classes=10, input_shape=(28, 28, 1), seed=12,
                  updater=Adam(learning_rate=1e-3)).init_graph()
    train = ImageMnist(MnistDataSetIterator(128, n_examples=20000))
    model.fit(train, n_epochs=4)
    test = ImageMnist(MnistDataSetIterator(256, n_examples=5000,
                                           train=False))
    acc = model.evaluate(test).accuracy()
    print(f"LeNet synthetic-MNIST test acc: {acc:.4f}")
    assert acc > 0.97, acc
    entry = save_pretrained(model, "LeNet", "mnist", out_dir)
    print("published:", entry)


def train_char_rnn(out_dir):
    from deeplearning4j_tpu.data.char_iterator import (
        CharacterIterator, sample_characters)
    from deeplearning4j_tpu.zoo import TextGenerationLSTM, save_pretrained

    text = ("the quick brown fox jumps over the lazy dog. "
            "pack my box with five dozen liquor jugs. " * 60)
    it = CharacterIterator(text, seq_length=40, batch=16, seed=3)
    model = TextGenerationLSTM(vocab_size=it.vocab_size, hidden=96,
                               n_layers=1, tbptt_length=20,
                               seed=7).init_graph()
    first = model.fit(it, n_epochs=1, async_prefetch=False)
    last = first
    for _ in range(24):
        last = model.fit(it, n_epochs=1, async_prefetch=False)
    print(f"char-RNN loss {first:.3f} -> {last:.3f}")
    assert last < first * 0.5, (first, last)
    sample = sample_characters(model, it, init="the ", n_chars=60,
                               temperature=0.3)
    print("sample:", repr(sample))
    entry = save_pretrained(model, "TextGenerationLSTM", "pangrams",
                            out_dir)
    # the sampler needs the char vocabulary — store it in the manifest
    import json
    mpath = entry["path"] + ".json"
    with open(mpath) as f:
        m = json.load(f)
    m["vocab"] = it.chars if isinstance(it.chars, str) else \
        "".join(it.chars)
    m["sha256"] = entry["sha256"]
    with open(mpath, "w") as f:
        json.dump(m, f)
    print("published:", entry)


def train_simple_cnn(out_dir):
    """SimpleCNN on (synthetic, see data/builtin.py) CIFAR-10 — the
    conv-net-at-CIFAR-scale registry entry (VERDICT r3 item 9)."""
    from deeplearning4j_tpu.data.builtin import Cifar10DataSetIterator
    from deeplearning4j_tpu.optimize.updaters import Adam
    from deeplearning4j_tpu.zoo import SimpleCNN, save_pretrained

    model = SimpleCNN(n_classes=10, input_shape=(32, 32, 3), seed=4,
                      updater=Adam(learning_rate=1e-3)).init_graph()
    train = Cifar10DataSetIterator(128, n_examples=8000, seed=11)
    model.fit(train, n_epochs=3)
    test = Cifar10DataSetIterator(256, train=False, n_examples=2000,
                                  seed=11)
    acc = model.evaluate(test).accuracy()
    print(f"SimpleCNN synthetic-CIFAR test acc: {acc:.4f}")
    assert acc > 0.9, acc
    entry = save_pretrained(model, "SimpleCNN", "cifar10-synthetic",
                            out_dir)
    print("published:", entry)


def train_gpt_char(out_dir):
    """Small causal char-LM via zoo.Gpt + KV-cache sampling — the
    transformer registry entry (VERDICT r3 item 9)."""
    import json

    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.models.generation import TransformerGenerator
    from deeplearning4j_tpu.optimize.updaters import Adam
    from deeplearning4j_tpu.zoo import save_pretrained
    from deeplearning4j_tpu.zoo.gpt import Gpt

    text = ("the quick brown fox jumps over the lazy dog. "
            "pack my box with five dozen liquor jugs. " * 40)
    chars = sorted(set(text))
    c2i = {c: i for i, c in enumerate(chars)}
    ids = np.asarray([c2i[c] for c in text], np.int32)
    t = 40
    starts = np.arange(0, len(ids) - t - 1, 7)
    xs = np.stack([ids[s:s + t] for s in starts])
    ys = np.stack([ids[s + 1:s + t + 1] for s in starts])

    model = Gpt(vocab_size=len(chars), max_len=64, d_model=64,
                n_layers=2, n_heads=4, d_ff=128, seq_len=t,
                compute_dtype=None, seed=9,
                updater=Adam(learning_rate=3e-3)).init_graph()
    rng = np.random.default_rng(0)
    first = last = None
    for epoch in range(30):
        order = rng.permutation(len(xs))
        for i in range(0, len(order), 32):
            b = order[i:i + 32]
            last = model.fit(DataSet(xs[b], ys[b]))
            if first is None:
                first = last
    print(f"char-GPT loss {first:.3f} -> {last:.3f}")
    assert last < 0.5 * first, (first, last)

    gen = TransformerGenerator(model)
    prompt = np.asarray([[c2i[c] for c in "the "]], np.int32)
    out = gen.generate(prompt, n_new=24)
    sample = "".join(chars[i] for i in out[0])
    print("sample:", repr(sample))

    entry = save_pretrained(model, "Gpt", "pangrams-char", out_dir)
    mpath = entry["path"] + ".json"
    with open(mpath) as f:
        m = json.load(f)
    m["vocab"] = "".join(chars)
    with open(mpath, "w") as f:
        json.dump(m, f)
    print("published:", entry)


def main():
    from deeplearning4j_tpu.zoo.pretrained import package_weights_dir
    out = package_weights_dir()
    os.makedirs(out, exist_ok=True)
    train_lenet(out)
    train_char_rnn(out)
    train_simple_cnn(out)
    train_gpt_char(out)


if __name__ == "__main__":
    main()
