#!/usr/bin/env python
"""KV-cache incremental-decode benchmark on the real chip ->
GENERATION_r05.json: steady-state decode rate for `zoo.Gpt` greedy
decoding through `models.generation.TransformerGenerator` (batched
prompt prefill + one jitted decode lax.scan; the transformer
``rnnTimeStep`` serving path), measured against the params-bandwidth
IDEAL for this chip — the number a decode step cannot beat because
every step must stream the full parameter set from HBM.

Protocol: the whole generate() call is ONE device program, so the
tunnel's per-call overhead is paid once; two call sizes (n_new 128 vs
512) difference out the prefill+fixed costs for the pure per-step
rate; different prompts per call (result-cache guard); best of 3.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

V5E_HBM_GBPS = 820.0          # v5e HBM bandwidth


def main():
    import jax
    from deeplearning4j_tpu.models.generation import TransformerGenerator
    from deeplearning4j_tpu.zoo.gpt import Gpt

    assert jax.default_backend() == "tpu", "needs the real chip"
    b, t0 = 8, 512
    m = Gpt(seq_len=t0, max_len=t0 + 512)
    net = m.init_graph()
    gen = TransformerGenerator(net, compute_dtype="bfloat16")
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, m.vocab_size, (b, t0)).astype(np.int32)
               for _ in range(8)]

    def timed(n_new, ps):
        _ = gen.generate(ps[0], n_new=n_new)          # compile
        best = 1e9
        for i in range(3):
            t_ = time.perf_counter()
            _ = gen.generate(ps[1 + i], n_new=n_new)
            best = min(best, time.perf_counter() - t_)
        return best

    t_short = timed(128, prompts[:4])
    t_long = timed(512, prompts[4:])
    per_step = (t_long - t_short) / (512 - 128)       # s per decode tick
    steps_per_sec = 1.0 / per_step
    new_tok_s = b * steps_per_sec                     # batched step

    # params-bandwidth ideal: every decode tick streams the params once
    n_params = sum(int(np.prod(np.shape(l)))
                   for l in jax.tree_util.tree_leaves(net.params_tree))
    bytes_per_step = 2.0 * n_params                   # bf16
    ideal_steps = V5E_HBM_GBPS * 1e9 / bytes_per_step
    result = {
        "metric": "gpt_kv_cache_decode",
        "model": "zoo.Gpt GPT-2-small-shaped (6x128 heads)",
        "batch": b, "prompt_len": t0,
        "prefill": "batched causal forward (r5; r4 consumed the "
                   "prompt one cached step at a time)",
        "seconds_per_call_128": round(t_short, 3),
        "seconds_per_call_512": round(t_long, 3),
        "decode_steps_per_sec": round(steps_per_sec, 1),
        "new_tokens_per_sec": round(new_tok_s, 1),
        "params": n_params,
        "params_bandwidth_ideal_steps_per_sec": round(ideal_steps, 1),
        "pct_of_bandwidth_ideal": round(
            100.0 * steps_per_sec / ideal_steps, 1),
        "note": "per-step rate from the (512-128)-tick call "
                "difference, so prefill and per-call tunnel costs "
                "cancel; the ideal line assumes one full bf16 "
                "parameter stream per tick (KV-cache reads add ~6% "
                "at these shapes and are not modeled)",
    }
    print(json.dumps(result))
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "GENERATION_r05.json")
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
    print("wrote", path)


if __name__ == "__main__":
    main()
