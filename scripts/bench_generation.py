#!/usr/bin/env python
"""KV-cache incremental-decode benchmark on the real chip ->
GENERATION_r04.json: steady-state tokens/sec for `zoo.Gpt` greedy
decoding through `models.generation.TransformerGenerator` (one jitted
lax.scan; the transformer ``rnnTimeStep`` serving path), plus the
full-prefix-recompute cost it replaces.

Protocol: the whole generate() call is ONE device program, so the
tunnel's per-call overhead is paid once; timing averages 3 calls after
a compile+warmup call, with different prompts per call (result-cache
guard).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def main():
    import jax
    from deeplearning4j_tpu.models.generation import TransformerGenerator
    from deeplearning4j_tpu.zoo.gpt import Gpt

    assert jax.default_backend() == "tpu", "needs the real chip"
    b, t0, n_new = 8, 512, 512
    m = Gpt(seq_len=t0, max_len=t0 + n_new)
    net = m.init_graph()
    gen = TransformerGenerator(net, compute_dtype="bfloat16")
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, m.vocab_size, (b, t0)).astype(np.int32)
               for _ in range(4)]

    out = gen.generate(prompts[0], n_new=n_new)       # compile
    t0_ = time.perf_counter()
    n_calls = 3
    for i in range(n_calls):
        out = gen.generate(prompts[1 + i], n_new=n_new)
    dt = (time.perf_counter() - t0_) / n_calls
    toks = b * (t0 + n_new - 1)       # scan steps per call
    new_toks = b * n_new
    result = {
        "metric": "gpt_kv_cache_decode",
        "model": "zoo.Gpt GPT-2-small-shaped (6x128 heads)",
        "batch": b, "prompt_len": t0, "new_tokens": n_new,
        "seconds_per_call": round(dt, 3),
        "decode_steps_per_sec": round(toks / dt, 1),
        "new_tokens_per_sec": round(new_toks / dt, 1),
        "note": "one jitted lax.scan per call: prefill rides the same "
                "cached step as sampling; a full-prefix-recompute "
                "greedy loop at these shapes costs O(t^2) forwards "
                "(512 full forwards of up to 1024 tokens vs 1023 "
                "cached single-token steps).",
    }
    print(json.dumps(result))
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "GENERATION_r04.json")
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
    print("wrote", path)


if __name__ == "__main__":
    main()
