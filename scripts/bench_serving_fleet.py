#!/usr/bin/env python
"""Multi-tenant serving-fleet benchmark -> SERVING_FLEET_r09.json:
1/2/4 ``GenerationServer`` replicas behind the ``ServingFleet``
admission router under a mixed 2-tenant load — a hot tenant sharing
one long system prompt (prefix-affinity should route it to the warm
replica) and a cold tenant with unique prompts (least-loaded spread).
Per rung: aggregate new-tokens/s, per-tenant TTFT p50/p99, and the
affinity hit rate.

Acceptance bar (ISSUE 9): the repeated-system-prompt tenant rides the
warm replica's prefix cache — affinity_hit_rate > 0 at every rung
with more than one replica (and at the 1-replica rung, where every
same-prefix dispatch is trivially affinity once seeded).

``--smoke`` runs the tiny CPU config (the artifact CI records —
JAX_PLATFORMS=cpu friendly); the default geometry needs the real
chip, where replicas map to chips and the ladder measures scaling
rather than router overhead.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    smoke = "--smoke" in sys.argv[1:]
    if not smoke:
        import jax
        assert jax.default_backend() == "tpu", \
            "needs the real chip (or pass --smoke for the CPU config)"
    from bench import bench_serving_fleet

    result = bench_serving_fleet(smoke=smoke)
    print(json.dumps(result))
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "SERVING_FLEET_r09.json")
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
    print("wrote", path)
    ok = all(r["affinity_hit_rate"] > 0 for r in result["ladder"])
    print("acceptance:", "OK" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
