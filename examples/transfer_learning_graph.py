#!/usr/bin/env python
"""DAG-side transfer learning (the reference's
``TransferLearning.GraphBuilder`` workflow): graph-ify the published
LeNet MLN weights (``mln_to_graph`` = upstream
``MultiLayerNetwork#toComputationGraph``), freeze the convolutional
featurizer by VERTEX name (ancestor closure), remove the 10-class
output vertex, attach a binary head, and fine-tune — plus the
``TransferLearningHelper`` featurizer split for cached-activation
head training."""
import numpy as np

from _common import example_args, setup_platform


def main():
    args = example_args(__doc__)
    setup_platform(args.smoke)

    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.mnist import MnistDataSetIterator
    from deeplearning4j_tpu.models.transfer_learning import (
        GraphBuilder, TransferLearningHelper, mln_to_graph)
    from deeplearning4j_tpu.nn.conf.layers_core import OutputLayer
    from deeplearning4j_tpu.optimize.updaters import Adam
    from deeplearning4j_tpu.zoo import load_pretrained

    graph = mln_to_graph(load_pretrained("LeNet", "mnist"))
    n_layers = len(graph.conf.topological_order)
    boundary = f"layer_{n_layers - 3}"
    ft = (GraphBuilder(graph)
          .set_feature_extractor(boundary)
          .remove_vertex_and_connections(f"layer_{n_layers - 1}")
          .add_layer("binary", OutputLayer(n_out=2, activation="softmax",
                                           loss="mcxent"),
                     f"layer_{n_layers - 2}")
          .set_outputs("binary")
          .fine_tune_configuration(updater=Adam(learning_rate=3e-3))
          .build())
    print("frozen vertices:", ft.conf.frozen_layers)

    n = 512 if args.smoke else 8000
    it = MnistDataSetIterator(64, n_examples=n, train=True)
    xs, labels = [], []
    for ds in it:
        xs.append(np.asarray(ds.features).reshape(-1, 28, 28, 1))
        labels.append((np.asarray(ds.labels).argmax(-1) < 5).astype(int))
    x = np.concatenate(xs)
    y = np.eye(2, dtype=np.float32)[np.concatenate(labels)]
    split = int(0.75 * len(x))
    epochs = 40 if args.smoke else 12
    for _ in range(epochs):
        ft.fit(DataSet(x[:split], y[:split]))
    pred = np.argmax(np.asarray(ft.output(x[split:])), -1)
    acc = (pred == y[split:].argmax(-1)).mean()
    print(f"held-out binary accuracy after fine-tune: {acc:.3f}")

    # featurizer split: frozen activations once, head-style reuse
    feats = TransferLearningHelper(ft, boundary).featurize(x[:8])
    print("featurized batch:", np.asarray(feats).shape)
    assert acc > 0.85, acc
    print("OK")


if __name__ == "__main__":
    main()
