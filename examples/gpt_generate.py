#!/usr/bin/env python
"""Causal decoder + KV-cache generation — the transformer analogue of
the char-RNN config's ``rnnTimeStep`` sampling loop: train a tiny
``zoo.Gpt`` on a copy task, then generate incrementally with per-layer
key/value caches (one jitted lax.scan, no per-token retrace)."""
import numpy as np

from _common import example_args, setup_platform


def main():
    args = example_args(__doc__)
    setup_platform(args.smoke)

    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.models.generation import TransformerGenerator
    from deeplearning4j_tpu.zoo.gpt import Gpt

    if args.smoke:
        m = Gpt(vocab_size=50, max_len=64, d_model=32, n_layers=2,
                n_heads=4, d_ff=64, seq_len=16, compute_dtype=None,
                seed=3)
        epochs = 30
    else:
        m = Gpt(vocab_size=32000, seq_len=512, max_len=1024)
        epochs = 5
    net = m.init_graph()
    rng = np.random.default_rng(0)
    x = rng.integers(0, m.vocab_size, (32, m.seq_len)).astype(np.int32)
    labels = np.roll(x, -1, axis=1).astype(np.int32)   # next-token
    first = net.fit(DataSet(x, labels))
    last = first
    for _ in range(epochs - 1):
        last = net.fit(DataSet(x, labels))
    print(f"loss {first:.3f} -> {last:.3f}")

    gen = TransformerGenerator(net)
    prompt = x[:2, :4]
    out = gen.generate(prompt, n_new=8)
    print("generated:", out.tolist())
    assert out.shape == (2, 12)
    assert np.isfinite(last) and last < first
    print("OK")


if __name__ == "__main__":
    main()
