#!/usr/bin/env python
"""BASELINE config 2 — ResNet-50 training via the zoo
(``deeplearning4j-zoo`` ComputationGraph analogue).  Full mode trains
ImageNet-shaped synthetic batches in bf16 on the chip (the bench.py
primary metric); --smoke runs a shrunken residual net on CPU."""
from _common import example_args, setup_platform


def main():
    args = example_args(__doc__)
    setup_platform(args.smoke)

    import numpy as np

    if args.smoke:
        from deeplearning4j_tpu.zoo.simple_cnn import SimpleCNN
        model = SimpleCNN(n_classes=10,
                          input_shape=(32, 32, 3)).init_graph()
        batch, hw, ncls, steps = 8, 32, 10, 3
    else:
        from deeplearning4j_tpu.zoo.resnet import ResNet50
        model = ResNet50(n_classes=1000,
                         input_shape=(224, 224, 3)).init_graph()
        batch, hw, ncls, steps = 256, 224, 1000, 30

    import time
    rng = np.random.default_rng(0)
    losses = []
    if args.smoke:
        from deeplearning4j_tpu.data.dataset import DataSet
        x = rng.normal(size=(batch, hw, hw, 3)).astype(np.float32)
        y = np.eye(ncls, dtype=np.float32)[rng.integers(0, ncls, batch)]
        t0 = time.perf_counter()
        for _ in range(steps):
            losses.append(float(model.fit(DataSet(x, y))))
        dt = time.perf_counter() - t0
    else:
        import jax.numpy as jnp
        x = jnp.asarray(rng.normal(size=(batch, hw, hw, 3)), jnp.bfloat16)
        y = jnp.asarray(np.eye(ncls, dtype=np.float32)[
            rng.integers(0, ncls, batch)])
        step = model.compiled_train_step()
        state = step.init()
        t0 = time.perf_counter()
        for _ in range(steps):
            state, loss = step(state, x, y)
            losses.append(float(loss))
        dt = time.perf_counter() - t0
    assert np.isfinite(losses).all()
    print(f"OK {steps} steps, loss {losses[0]:.3f} -> {losses[-1]:.3f}, "
          f"{batch * steps / dt:.1f} img/s")


if __name__ == "__main__":
    main()
