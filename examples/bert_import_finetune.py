#!/usr/bin/env python
"""BASELINE config 4 — TF BERT import + SST-2-style fine-tune
(``SameDiff`` TF import path): load a frozen pb, rewrite attention
subgraphs onto the Pallas flash kernel, attach a 2-class head, and
fine-tune in bf16 AMP.

--smoke uses the committed 2-layer tiny-BERT fixture; full mode
generates/caches the ~438 MB BERT-base fixture and mirrors the
``bench.py`` imported-fine-tune benchmark."""
import os

import numpy as np

from _common import example_args, setup_platform


def main():
    args = example_args(__doc__)
    setup_platform(args.smoke)

    from deeplearning4j_tpu.autodiff import TrainingConfig
    from deeplearning4j_tpu.autodiff.rewrites import fuse_attention
    from deeplearning4j_tpu.autodiff.tf_import import import_frozen_pb
    from deeplearning4j_tpu.data.dataset import MultiDataSet
    from deeplearning4j_tpu.optimize.updaters import Adam

    if args.smoke:
        pb = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          os.pardir, "tests", "fixtures",
                          "bert_tiny_frozen.pb")
        t, n_expect = 16, 2
    else:
        from deeplearning4j_tpu.utils.bert_fixture import (
            ensure_bert_base_fixture)
        pb, _ = ensure_bert_base_fixture(t=512)
        t, n_expect = 512, 12

    sd = import_frozen_pb(pb)
    n_fused = fuse_attention(sd)
    print(f"fused {n_fused} attention sites")
    assert n_fused == n_expect, n_fused

    d_model = 64 if args.smoke else 768
    feeds = ["i", "m", "t"]
    pooled = sd.vars["Identity_1"]

    w = sd.var("cls_W", np.random.default_rng(0).normal(
        scale=0.02, size=(d_model, 2)).astype(np.float32))
    b = sd.var("cls_b", np.zeros(2, np.float32))
    logits = sd.op("add", sd.matmul(pooled, w), b, name="logits")
    labels = sd.placeholder("labels", (None,), "int32")
    per_ex = sd.op("sparse_softmax_cross_entropy_with_logits", labels,
                   logits)
    sd.set_loss_variables(sd.reduce_mean(per_ex, name="loss"))
    sd.set_training_config(TrainingConfig(
        updater=Adam(learning_rate=2e-5),
        data_set_feature_mapping=feeds,
        data_set_label_mapping=["labels"],
        compute_dtype="bfloat16"))

    rng = np.random.default_rng(0)
    batch = 4 if args.smoke else 32
    ids = rng.integers(0, 500, (batch, t)).astype(np.int32)
    lab = rng.integers(0, 2, batch).astype(np.int32)
    mask = np.ones((batch, t), np.int32)
    tt = np.zeros((batch, t), np.int32)
    ds = MultiDataSet([ids, mask, tt], [lab])
    losses = sd.fit([ds] * (2 if args.smoke else 10), n_epochs=1)
    print(f"loss {losses[0]:.4f} -> {losses[-1]:.4f}")
    assert np.isfinite(losses).all()
    print("OK")


if __name__ == "__main__":
    main()
