#!/usr/bin/env python
"""BASELINE config 5 — data-parallel training over the device mesh
(the ``SharedTrainingMaster`` grad-sharing path re-designed as GSPMD:
shardings + XLA all-reduce over ICI, no parameter server).
--smoke runs ResNet-18 over a virtual 8-device CPU mesh."""
from _common import example_args, setup_platform


def main():
    args = example_args(__doc__)
    setup_platform(args.smoke)

    import jax
    import numpy as np

    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.parallel.mesh import MeshConfig
    from deeplearning4j_tpu.parallel.trainer import ShardedTrainer
    from deeplearning4j_tpu.zoo.simple_cnn import SimpleCNN

    n_dev = len(jax.devices())
    model = SimpleCNN(n_classes=10,
                      input_shape=(32, 32, 3)).init_graph()
    trainer = ShardedTrainer(model, MeshConfig(data=n_dev))

    rng = np.random.default_rng(0)
    batch = 8 * n_dev
    steps = 3 if args.smoke else 50
    losses = []
    for _ in range(steps):
        x = rng.normal(size=(batch, 32, 32, 3)).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)]
        losses.append(float(trainer.fit_batch(x, y)))
    print(f"{n_dev}-way DP, loss {losses[0]:.3f} -> {losses[-1]:.3f}")
    assert np.isfinite(losses).all()
    print("OK")


if __name__ == "__main__":
    main()
