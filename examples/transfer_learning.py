#!/usr/bin/env python
"""Transfer learning from published zoo weights (the reference's
``TransferLearning`` + zoo-pretrained flagship workflow): load the
in-repo LeNet MNIST weights, freeze the convolutional feature
extractor, swap the 10-class head for a binary one, fine-tune."""
import numpy as np

from _common import example_args, setup_platform


def main():
    args = example_args(__doc__)
    setup_platform(args.smoke)

    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.mnist import MnistDataSetIterator
    from deeplearning4j_tpu.models.transfer_learning import (
        TransferLearning, frozen_layer_indices)
    from deeplearning4j_tpu.nn.conf.layers_core import OutputLayer
    from deeplearning4j_tpu.optimize.updaters import Adam
    from deeplearning4j_tpu.zoo import load_pretrained

    m = load_pretrained("LeNet", "mnist")
    ft = (TransferLearning.Builder(m)
          .fine_tune_configuration(updater=Adam(learning_rate=1e-3))
          .set_feature_extractor(len(m.layers) - 3)
          .remove_output_layer_and_processing()
          .add_layer(OutputLayer(n_out=2, activation="softmax",
                                 loss="mcxent"))
          .build())
    print("frozen layers:", frozen_layer_indices(ft))

    n = 2000 if args.smoke else 20000
    it = MnistDataSetIterator(128, n_examples=n, train=True)
    losses = []
    for _ in range(2):
        for ds in it:
            x = np.asarray(ds.features).reshape(-1, 28, 28, 1)
            lab = (np.asarray(ds.labels).argmax(-1) < 5).astype(int)
            losses.append(float(ft.fit(
                DataSet(x, np.eye(2, dtype=np.float32)[lab]))))
        it.reset()
    test = next(iter(MnistDataSetIterator(512, n_examples=512,
                                          train=False)))
    xs = np.asarray(test.features).reshape(-1, 28, 28, 1)
    lab = (np.asarray(test.labels).argmax(-1) < 5).astype(int)
    acc = (np.asarray(ft.output(xs)).argmax(-1) == lab).mean()
    print(f"loss {losses[0]:.3f} -> {losses[-1]:.3f}, "
          f"binary accuracy {acc:.4f}")
    assert acc > 0.95, acc
    print("OK")


if __name__ == "__main__":
    main()
