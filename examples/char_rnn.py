#!/usr/bin/env python
"""BASELINE config 3 — GravesLSTM char-RNN language model
(dl4j-examples ``LSTMCharModellingExample``): CharacterIterator +
TextGenerationLSTM + temperature sampling."""
from _common import example_args, setup_platform


def main():
    args = example_args(__doc__)
    setup_platform(args.smoke)

    from deeplearning4j_tpu.data.char_iterator import (
        CharacterIterator, sample_characters)
    from deeplearning4j_tpu.zoo import TextGenerationLSTM

    text = ("the quick brown fox jumps over the lazy dog. " * 200)
    seq, hidden, epochs = ((30, 64, 4) if args.smoke else (64, 256, 30))
    it = CharacterIterator(text, seq_length=seq, batch=16, seed=1)
    model = TextGenerationLSTM(vocab_size=it.vocab_size, hidden=hidden,
                               n_layers=2, tbptt_length=seq // 2,
                               seed=5).init_graph()
    first = model.fit(it, n_epochs=1, async_prefetch=False)
    last = first
    for _ in range(epochs - 1):
        last = model.fit(it, n_epochs=1, async_prefetch=False)
    sample = sample_characters(model, it, init="the ", n_chars=120,
                               temperature=0.6)
    print(f"loss {first:.3f} -> {last:.3f}")
    print(f"sample: {sample!r}")
    assert last < first
    print("OK")


if __name__ == "__main__":
    main()
