"""Shared example bootstrap.

The reference's public face is the separate ``dl4j-examples`` repo;
these scripts are its TPU-native equivalent, one per BASELINE.json
config.  Every example takes ``--smoke``: tiny shapes on a virtual
8-device CPU mesh, exactly what CI runs (``tests/test_examples.py``).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def example_args(description: str) -> argparse.Namespace:
    p = argparse.ArgumentParser(description=description)
    p.add_argument("--smoke", action="store_true",
                   help="tiny shapes, CPU virtual 8-device mesh (CI)")
    return p.parse_args()


def setup_platform(smoke: bool) -> None:
    """--smoke forces the CPU platform BEFORE jax initializes (the
    axon sitecustomize pins the TPU plugin; env vars alone are not
    enough)."""
    if not smoke:
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
