#!/usr/bin/env python
"""BASELINE config 1 — MNIST MLP (dl4j-examples
``MLPMnistSingleLayerExample``): 784 -> 500(relu) -> 10(softmax,NLL),
Nesterovs(0.006, 0.9), l2=1e-4.  One fused XLA training step per
batch; >97% test accuracy at full size."""
from _common import example_args, setup_platform


def main():
    args = example_args(__doc__)
    setup_platform(args.smoke)

    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.data.mnist import MnistDataSetIterator
    from deeplearning4j_tpu.nn.conf.layers_core import (DenseLayer,
                                                        OutputLayer)
    from deeplearning4j_tpu.optimize.listeners import ScoreIterationListener
    from deeplearning4j_tpu.optimize.updaters import Nesterovs

    n_train = 8000 if args.smoke else 60000
    n_epochs = 2 if args.smoke else 5
    train = MnistDataSetIterator(128, train=True, seed=123,
                                 n_examples=n_train)
    test = MnistDataSetIterator(512, train=False, seed=123,
                                n_examples=max(n_train // 6, 500))

    conf = (NeuralNetConfiguration.builder()
            .seed(123)
            .updater(Nesterovs(learning_rate=0.006, momentum=0.9))
            .l2(1e-4)
            .weight_init("xavier")
            .list()
            .layer(DenseLayer(n_in=784, n_out=500, activation="relu"))
            .layer(OutputLayer(n_out=10, activation="softmax",
                               loss="negativeloglikelihood"))
            .build())
    model = MultiLayerNetwork(conf).init()
    model.set_listeners(ScoreIterationListener(50))
    model.fit(train, n_epochs=n_epochs)
    ev = model.evaluate(test)
    print(ev.stats())
    bar = 0.9 if args.smoke else 0.97
    assert ev.accuracy() > bar, ev.accuracy()
    print(f"OK accuracy={ev.accuracy():.4f}")


if __name__ == "__main__":
    main()
