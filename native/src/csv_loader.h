/* Native CSV loader — the C ABI surface of the framework's native IO
 * core.
 *
 * Role in the architecture: the reference keeps its hot IO in native
 * code (libnd4j NativeOps buffer plumbing + JavaCV decode behind
 * DataVec); here the XLA/PJRT runtime owns all device compute, so the
 * native layer's job is HOST-side ETL throughput — parsing numeric
 * text into ready-to-transfer float32 batches without Python
 * object-per-cell overhead.  Exposed as a plain C ABI consumed via
 * ctypes (the JavaCPP/JNI analogue, minus codegen).
 *
 * All functions return 0 on success, negative error codes otherwise.
 */
#ifndef DL4J_TPU_CSV_LOADER_H
#define DL4J_TPU_CSV_LOADER_H

#include <cstdint>

extern "C" {

/* Scan the file: number of data rows (after skip_lines, ignoring empty
 * lines) and columns (from the first data row). */
int dl4j_csv_dims(const char* path, int skip_lines, char delimiter,
                  int64_t* n_rows, int64_t* n_cols);

/* Parse the full file into a row-major float32 matrix [n_rows, n_cols]
 * (buffer preallocated by the caller).  Non-numeric cells fail with -3.
 * n_threads > 1 splits the file into line-aligned chunks parsed in
 * parallel (std::thread). */
int dl4j_csv_parse(const char* path, int skip_lines, char delimiter,
                   float* out, int64_t n_rows, int64_t n_cols,
                   int n_threads);

/* uint8 HWC image batch -> float32 scaled by 1/255 (the
 * ImagePreProcessingScaler hot loop, SIMD-vectorized by the compiler). */
void dl4j_u8_to_f32_scaled(const uint8_t* src, float* dst, int64_t n,
                           float scale);

}  /* extern "C" */

#endif
