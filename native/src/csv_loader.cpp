#include "csv_loader.h"

#include <cerrno>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

/* Whole-file read (streaming would complicate chunk splitting; training
 * CSVs fit host RAM by construction — they become one device batch). */
int read_file(const char* path, std::string* out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  out->resize(static_cast<size_t>(size));
  size_t got = size ? std::fread(&(*out)[0], 1, size, f) : 0;
  std::fclose(f);
  return got == static_cast<size_t>(size) ? 0 : -2;
}

/* A char that may appear on a "blank" line; the active delimiter is
 * never blank (a leading empty field like "\t1\t2" must survive). */
inline bool is_blank_char(char c, char delim) {
  /* Space stays blank even for delimiter ' ' (empty space-delimited
   * fields are unrepresentable — strtof skips spaces — so only a tab
   * delimiter needs protecting from the blank set). */
  return c == '\r' || c == ' ' || (c == '\t' && delim != '\t');
}

/* [start, end) line-aligned offsets of data lines after skip_lines. */
void data_region(const std::string& buf, int skip_lines, size_t* start) {
  size_t pos = 0;
  for (int i = 0; i < skip_lines && pos < buf.size(); ++i) {
    size_t nl = buf.find('\n', pos);
    pos = (nl == std::string::npos) ? buf.size() : nl + 1;
  }
  *start = pos;
}

int parse_lines(const char* p, const char* end, char delim, float* out,
                int64_t n_cols, int64_t max_rows, int64_t* rows_done) {
  int64_t row = 0;
  while (p < end) {
    /* skip empty/blank-only line content (the same "empty" rule as
     * dl4j_csv_dims, which does not count such lines as rows) */
    while (p < end && (*p == '\n' || is_blank_char(*p, delim))) ++p;
    if (p >= end) break;
    if (row >= max_rows) return -5;  /* more data than the caller sized */
    for (int64_t c = 0; c < n_cols; ++c) {
      if (c > 0) {
        /* strtof skips leading whitespace INCLUDING '\n', so a ragged
         * row with a trailing empty field would silently consume the
         * next line's first value; fail deterministically instead. */
        const char* scan = p;
        while (scan < end && (*scan == ' ' || *scan == '\t' ||
                              *scan == '\r' || *scan == '\v' ||
                              *scan == '\f'))
          ++scan;
        if (scan >= end || *scan == '\n') return -4;
      }
      char* next = nullptr;
      errno = 0;
      float v = std::strtof(p, &next);
      if (next == p || errno == ERANGE) return -3;
      out[row * n_cols + c] = v;
      p = next;
      if (c + 1 < n_cols) {
        if (p < end && *p == delim) ++p;
        else return -4; /* too few columns */
      }
    }
    while (p < end && *p != '\n') ++p; /* trailing cr/extra ignored */
    if (p < end) ++p;
    ++row;
  }
  *rows_done = row;
  return 0;
}

}  // namespace

extern "C" {

int dl4j_csv_dims(const char* path, int skip_lines, char delimiter,
                  int64_t* n_rows, int64_t* n_cols) {
  std::string buf;
  int rc = read_file(path, &buf);
  if (rc) return rc;
  size_t start;
  data_region(buf, skip_lines, &start);
  int64_t rows = 0, cols = 0;
  bool first = true;
  size_t pos = start;
  while (pos < buf.size()) {
    size_t nl = buf.find('\n', pos);
    size_t line_end = (nl == std::string::npos) ? buf.size() : nl;
    bool empty = true;
    for (size_t i = pos; i < line_end; ++i)
      if (!is_blank_char(buf[i], delimiter)) { empty = false; break; }
    if (!empty) {
      ++rows;
      if (first) {
        cols = 1;
        for (size_t i = pos; i < line_end; ++i)
          if (buf[i] == delimiter) ++cols;
        first = false;
      }
    }
    pos = (nl == std::string::npos) ? buf.size() : nl + 1;
  }
  *n_rows = rows;
  *n_cols = cols;
  return 0;
}

int dl4j_csv_parse(const char* path, int skip_lines, char delimiter,
                   float* out, int64_t n_rows, int64_t n_cols,
                   int n_threads) {
  std::string buf;
  int rc = read_file(path, &buf);
  if (rc) return rc;
  size_t start;
  data_region(buf, skip_lines, &start);
  const char* base = buf.data();
  const char* end = base + buf.size();

  if (n_threads <= 1) {
    int64_t done = 0;
    rc = parse_lines(base + start, end, delimiter, out, n_cols, n_rows,
                     &done);
    if (rc) return rc;
    return done == n_rows ? 0 : -5;
  }

  /* line-aligned chunk boundaries with their starting row index; a
   * line counts as a row ONLY under the same rule dl4j_csv_dims uses
   * (some non-{'\r',' '} char), so chunk write offsets can never
   * drift past the caller's n_rows allocation. */
  std::vector<size_t> bounds{start};
  std::vector<int64_t> row_at{0};
  int64_t rows_seen = 0;
  size_t pos = start;
  size_t target = (buf.size() - start) / n_threads;
  size_t next_cut = start + target;
  while (pos < buf.size()) {
    size_t nl = buf.find('\n', pos);
    size_t line_end = (nl == std::string::npos) ? buf.size() : nl;
    bool empty = true;
    for (size_t i = pos; i < line_end; ++i)
      if (!is_blank_char(buf[i], delimiter)) { empty = false; break; }
    if (!empty) ++rows_seen;
    pos = (nl == std::string::npos) ? buf.size() : nl + 1;
    if (pos >= next_cut && pos < buf.size() &&
        bounds.size() < static_cast<size_t>(n_threads)) {
      bounds.push_back(pos);
      row_at.push_back(rows_seen);
      next_cut = pos + target;
    }
  }
  bounds.push_back(buf.size());
  if (rows_seen != n_rows) return -5;

  std::vector<int> rcs(bounds.size() - 1, 0);
  std::vector<int64_t> dones(bounds.size() - 1, 0);
  std::vector<std::thread> threads;
  for (size_t t = 0; t < bounds.size() - 1; ++t) {
    threads.emplace_back([&, t]() {
      int64_t quota = ((t + 1 < row_at.size()) ? row_at[t + 1] : n_rows)
                      - row_at[t];
      rcs[t] = parse_lines(base + bounds[t], base + bounds[t + 1],
                           delimiter, out + row_at[t] * n_cols, n_cols,
                           quota, &dones[t]);
    });
  }
  for (auto& th : threads) th.join();
  for (int r : rcs)
    if (r) return r;
  /* every chunk must have parsed exactly the rows allotted to it */
  for (size_t t = 0; t < dones.size(); ++t) {
    int64_t expect = ((t + 1 < row_at.size()) ? row_at[t + 1] : n_rows)
                     - row_at[t];
    if (dones[t] != expect) return -5;
  }
  return 0;
}

void dl4j_u8_to_f32_scaled(const uint8_t* src, float* dst, int64_t n,
                           float scale) {
  for (int64_t i = 0; i < n; ++i) dst[i] = src[i] * scale;
}

}  /* extern "C" */
