/* Native-core tests (the libnd4j tests_cpu/ role, assert-harness since
 * the image ships no gtest and has no egress). */
#include "../src/csv_loader.h"

#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

static int failures = 0;
#define CHECK(cond)                                                     \
  do {                                                                  \
    if (!(cond)) {                                                      \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,      \
                   #cond);                                              \
      ++failures;                                                       \
    }                                                                   \
  } while (0)

static std::string write_tmp(const char* content) {
  std::string path = "/tmp/dl4j_native_test_XXXXXX";
  int fd = mkstemp(&path[0]);
  FILE* f = fdopen(fd, "w");
  std::fputs(content, f);
  std::fclose(f);
  return path;
}

static void test_dims_and_parse() {
  std::string p = write_tmp("# header\n1,2.5,3\n-4,5e-1,6\n\n7,8,9\n");
  int64_t rows = 0, cols = 0;
  CHECK(dl4j_csv_dims(p.c_str(), 1, ',', &rows, &cols) == 0);
  CHECK(rows == 3);
  CHECK(cols == 3);
  float out[9];
  CHECK(dl4j_csv_parse(p.c_str(), 1, ',', out, rows, cols, 1) == 0);
  CHECK(out[0] == 1.0f);
  CHECK(std::fabs(out[1] - 2.5f) < 1e-6);
  CHECK(out[3] == -4.0f);
  CHECK(std::fabs(out[4] - 0.5f) < 1e-6);
  CHECK(out[8] == 9.0f);
  std::remove(p.c_str());
}

static void test_threaded_matches_serial() {
  std::string content;
  for (int i = 0; i < 1000; ++i) {
    char line[64];
    std::snprintf(line, sizeof line, "%d,%d.5,%d\n", i, i, i * 2);
    content += line;
    if (i % 97 == 0) content += "  \r\n";  /* junk whitespace lines */
  }
  std::string p = write_tmp(content.c_str());
  int64_t rows, cols;
  CHECK(dl4j_csv_dims(p.c_str(), 0, ',', &rows, &cols) == 0);
  CHECK(rows == 1000 && cols == 3);
  std::string serial(rows * cols * 4, '\0'), par(rows * cols * 4, '\0');
  float* s = reinterpret_cast<float*>(&serial[0]);
  float* m = reinterpret_cast<float*>(&par[0]);
  CHECK(dl4j_csv_parse(p.c_str(), 0, ',', s, rows, cols, 1) == 0);
  CHECK(dl4j_csv_parse(p.c_str(), 0, ',', m, rows, cols, 4) == 0);
  CHECK(std::memcmp(s, m, rows * cols * 4) == 0);
  std::remove(p.c_str());
}

static void test_trailing_whitespace_line() {
  std::string p = write_tmp("1,2\n  \n");
  int64_t rows, cols;
  CHECK(dl4j_csv_dims(p.c_str(), 0, ',', &rows, &cols) == 0);
  CHECK(rows == 1 && cols == 2);
  float out[2];
  CHECK(dl4j_csv_parse(p.c_str(), 0, ',', out, rows, cols, 1) == 0);
  CHECK(out[0] == 1.0f && out[1] == 2.0f);
  std::remove(p.c_str());
}

static void test_tab_lines_and_tab_delimiter() {
  /* tab-only line is blank for comma CSVs */
  std::string p = write_tmp("1,2\n\t\n3,4\n");
  int64_t rows, cols;
  CHECK(dl4j_csv_dims(p.c_str(), 0, ',', &rows, &cols) == 0);
  CHECK(rows == 2 && cols == 2);
  float out[4];
  CHECK(dl4j_csv_parse(p.c_str(), 0, ',', out, rows, cols, 1) == 0);
  CHECK(out[2] == 3.0f);
  std::remove(p.c_str());
  /* tab DELIMITER: leading empty field must not be eaten... strtof on
   * an empty field fails -3, which is at least loud, but a normal
   * tab-separated file parses fine */
  std::string p2 = write_tmp("1\t2\n3\t4\n");
  CHECK(dl4j_csv_dims(p2.c_str(), 0, '\t', &rows, &cols) == 0);
  CHECK(rows == 2 && cols == 2);
  CHECK(dl4j_csv_parse(p2.c_str(), 0, '\t', out, rows, cols, 1) == 0);
  CHECK(out[1] == 2.0f && out[3] == 4.0f);
  std::remove(p2.c_str());
}

static void test_space_delimiter_trailing_blank() {
  std::string p = write_tmp("1 2\n3 4\n   \n");
  int64_t rows, cols;
  CHECK(dl4j_csv_dims(p.c_str(), 0, ' ', &rows, &cols) == 0);
  CHECK(rows == 2 && cols == 2);
  float out[4];
  CHECK(dl4j_csv_parse(p.c_str(), 0, ' ', out, rows, cols, 1) == 0);
  CHECK(out[3] == 4.0f);
  std::remove(p.c_str());
}

static void test_undersized_buffer_rejected() {
  std::string p = write_tmp("1,2\n3,4\n5,6\n");
  float out[4];  /* claim 2 rows although the file has 3 */
  CHECK(dl4j_csv_parse(p.c_str(), 0, ',', out, 2, 2, 1) == -5);
  std::remove(p.c_str());
}

static void test_ragged_row_fails_deterministically() {
  /* A row with a trailing empty field ("1,2," with 3 declared cols)
   * must error -4, not let strtof skip the newline and consume the
   * next line's first value (advisor round 2). */
  std::string p = write_tmp("1,2,3\n4,5,\n6,7,8\n");
  int64_t rows, cols;
  CHECK(dl4j_csv_dims(p.c_str(), 0, ',', &rows, &cols) == 0);
  CHECK(rows == 3 && cols == 3);
  float out[9];
  CHECK(dl4j_csv_parse(p.c_str(), 0, ',', out, rows, cols, 1) == -4);
  /* same failure when the ragged line ends a thread's chunk */
  std::string content;
  for (int i = 0; i < 500; ++i) content += "1,2,3\n";
  content += "4,5,\n";
  for (int i = 0; i < 500; ++i) content += "6,7,8\n";
  std::string p2 = write_tmp(content.c_str());
  CHECK(dl4j_csv_dims(p2.c_str(), 0, ',', &rows, &cols) == 0);
  std::string buf(rows * cols * 4, '\0');
  float* o = reinterpret_cast<float*>(&buf[0]);
  CHECK(dl4j_csv_parse(p2.c_str(), 0, ',', o, rows, cols, 4) == -4);
  std::remove(p.c_str());
  std::remove(p2.c_str());
}

static void test_errors() {
  std::string p = write_tmp("1,abc,3\n");
  int64_t rows, cols;
  CHECK(dl4j_csv_dims(p.c_str(), 0, ',', &rows, &cols) == 0);
  float out[3];
  CHECK(dl4j_csv_parse(p.c_str(), 0, ',', out, rows, cols, 1) == -3);
  std::remove(p.c_str());
  int64_t r2, c2;
  CHECK(dl4j_csv_dims("/nonexistent/file.csv", 0, ',', &r2, &c2) == -1);
}

static void test_u8_scale() {
  uint8_t src[4] = {0, 51, 102, 255};
  float dst[4];
  dl4j_u8_to_f32_scaled(src, dst, 4, 1.0f / 255.0f);
  CHECK(std::fabs(dst[0]) < 1e-9);
  CHECK(std::fabs(dst[1] - 0.2f) < 1e-6);
  CHECK(std::fabs(dst[3] - 1.0f) < 1e-6);
}

int main() {
  test_dims_and_parse();
  test_threaded_matches_serial();
  test_trailing_whitespace_line();
  test_tab_lines_and_tab_delimiter();
  test_space_delimiter_trailing_blank();
  test_undersized_buffer_rejected();
  test_ragged_row_fails_deterministically();
  test_errors();
  test_u8_scale();
  if (failures) {
    std::fprintf(stderr, "%d failure(s)\n", failures);
    return 1;
  }
  std::puts("ALL NATIVE TESTS PASSED");
  return 0;
}
